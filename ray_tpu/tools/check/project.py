"""Cross-file conformance rules: RPC registries, failpoints, metrics.

These rules check agreement between *places that must not drift apart*:

* ``rpc-conformance`` — a client-side method string with no
  ``handle_<method>`` coroutine anywhere is a call that can only ever
  produce a remote "no method" error; an ``IDEMPOTENT_METHODS`` entry
  with no handler is a stale registry line that silently licenses
  retry-after-send for a method that no longer exists; a control-plane
  handler (gcs/raylet/worker) missing a ``messages.py`` schema is a
  typed-boundary hole — its payloads cross processes unvalidated.
* ``failpoint-registry`` — failpoint site names must be unique (two
  sites sharing a name are armed together: a chaos test thinks it
  injected one fault and injected two) and documented in
  ``docs/fault_injection.md`` (an undocumented site is invisible to
  anyone writing chaos coverage).
* ``metric-drift`` — every ``ray_tpu_*`` series constructed in code must
  appear in ``scripts/metrics_golden.txt``, the exporter catalogue that
  dashboards and the metrics smoke test key on.  A name typo'd or added
  without updating the catalogue ships a series nobody scrapes.
* ``persist-conformance`` — a GCS handler that mutates a persisted
  table (kv, jobs, functions, actors, named actors, placement groups,
  node membership) without reaching the WAL / snapshot scheduler is a
  durability hole: the mutation is acked to the client and silently
  lost on the next head restart.  Mutation and persistence are both
  resolved transitively through same-class helper calls, so
  ``handle_register_actor → _register_one_actor → _schedule_persist``
  conforms without annotations.
* ``trace-propagation`` — RPC call sites on the serve request path and
  in the worker's submit-path functions must forward the distributed
  trace context (a ``trace`` payload key or a spec blob); a site that
  drops it silently truncates every assembled trace at that hop.
* ``flight-vocab`` — every literal event type passed to the flight
  recorder's ``record`` must be declared in its ``EVENT_TYPES``
  catalogue; an undeclared type silently degrades to ``mark`` at
  runtime and vanishes from the postmortem legend.
* ``step-instrumentation`` — engine classes exposing a compiled step
  entry point (``step`` / ``shard_step`` / ``decode_step`` /
  ``train_step`` / ``compute_actions``) must wrap every ``jax.jit``
  they bind to an attribute in ``device_telemetry.instrument_step``;
  an unwrapped jit's compiles never reach the device plane, so a
  recompile storm in that engine is invisible to the RecompileStorm
  alert.

All checks are static (AST + text); nothing here imports runtime
modules, so the analyzer runs in CI without booting a cluster.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from ray_tpu.tools.check.astrules import ModuleContext, _dotted
from ray_tpu.tools.check.findings import Finding, parse_catalogue

__all__ = ["ProjectConfig", "check_rpc_conformance",
           "check_failpoint_registry", "check_metric_drift",
           "check_trace_propagation", "check_persist_conformance",
           "check_step_instrumentation", "check_flight_vocab",
           "collect_metric_names", "parse_catalogue", "PROJECT_RULES"]


@dataclass
class ProjectConfig:
    """Repo-layout knobs, overridable so tests can point the rules at
    fixture trees."""

    root: str = "."
    #: services whose handlers form the typed control plane (schema
    #: coverage is enforced here; the ray:// client proxy opts out of
    #: schema validation and is exempt)
    core_service_files: Tuple[str, ...] = (
        "ray_tpu/core/gcs.py", "ray_tpu/core/raylet.py",
        "ray_tpu/core/worker.py")
    messages_path: str = "ray_tpu/core/messages.py"
    rpc_path: str = "ray_tpu/core/rpc.py"
    failpoint_doc: str = "docs/fault_injection.md"
    metrics_golden: str = "scripts/metrics_golden.txt"
    #: trace-propagation scope: every RPC call site under these dirs
    #: (the serve request path) ...
    trace_scope_dirs: Tuple[str, ...] = ("ray_tpu/serve/",)
    #: ... plus these submit-path functions of the worker (the file is
    #: huge; only its task/actor/lease submission chain carries traces)
    trace_worker_file: str = "ray_tpu/core/worker.py"
    trace_worker_funcs: Tuple[str, ...] = (
        "_request_lease_chain", "_push_task", "_push_task_batch",
        "create_actor", "_start_single_push", "_send_actor_batch")
    #: persist-conformance scope: the GCS service file, its persisted
    #: table attributes, and the calls that count as reaching the
    #: durable tier (WAL append or snapshot schedule)
    persist_service_file: str = "ray_tpu/core/gcs.py"
    persist_tables: Tuple[str, ...] = (
        "kv", "jobs", "job_counter", "functions", "actors",
        "named_actors", "placement_groups", "nodes",
        "quotas", "lease_tables", "_node_states", "_incidents")
    #: flight-vocab scope: the module declaring the EVENT_TYPES
    #: catalogue every ``_flight.record(...)`` literal must appear in
    flight_module: str = "ray_tpu/core/flight_recorder.py"
    persist_calls: Tuple[str, ...] = (
        "_schedule_persist", "_persist_now", "_wal_append", "_wal_flush",
        "_wal_actor", "_wal_pg", "_wal_job")
    #: step-instrumentation scope: classes exposing one of these
    #: compiled step entry points must route every ``jax.jit`` they
    #: bind to an attribute through a device-telemetry wrapper, or the
    #: engine's compiles are invisible to the device plane
    step_entry_points: Tuple[str, ...] = (
        "step", "shard_step", "decode_step", "train_step",
        "compute_actions")
    device_wrapper_names: Tuple[str, ...] = ("instrument_step",)
    #: memoized ProjectIndex for this run — set lazily by
    #: ``ipa.index_for`` (the CLI pre-populates it with the disk-cached
    #: index so rules and registries share one build)
    ipa_index: Optional[object] = None

    def read(self, rel: str) -> Optional[str]:
        try:
            with open(os.path.join(self.root, rel)) as f:
                return f.read()
        except OSError:
            return None


def _str_arg(call: ast.Call, index: int) -> Optional[str]:
    if len(call.args) > index and isinstance(call.args[index], ast.Constant) \
            and isinstance(call.args[index].value, str):
        return call.args[index].value
    return None


# ---------------------------------------------------------------------------
# rpc-conformance
# ---------------------------------------------------------------------------

def _collect_schemas(cfg: ProjectConfig) -> Set[str]:
    """Methods registered via ``register_schema("name", ...)`` in
    messages.py — parsed statically so the analyzer never imports
    runtime code."""
    src = cfg.read(cfg.messages_path)
    if src is None:
        return set()
    out: Set[str] = set()
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None and d.split(".")[-1] == "register_schema":
                name = _str_arg(node, 0)
                if name:
                    out.add(name)
    return out


def _collect_idempotent(cfg: ProjectConfig) -> Tuple[Set[str], int]:
    """(methods, line) of the IDEMPOTENT_METHODS frozenset in rpc.py."""
    src = cfg.read(cfg.rpc_path)
    if src is None:
        return set(), 0
    for node in ast.walk(ast.parse(src)):
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "IDEMPOTENT_METHODS"
                        for t in node.targets):
            methods = {c.value for c in ast.walk(node.value)
                       if isinstance(c, ast.Constant)
                       and isinstance(c.value, str)}
            return methods, node.lineno
    return set(), 0


def _walked(ctx: ModuleContext) -> List[ast.AST]:
    """Every AST node of the module, walked once and cached on the
    context: eight cross-file collectors each iterate every node of
    every module, and re-walking ~200 trees per collector dominated
    the warm-cache runtime."""
    nodes = ctx.__dict__.get("_walked_nodes")
    if nodes is None:
        nodes = list(ast.walk(ctx.tree))
        ctx.__dict__["_walked_nodes"] = nodes
    return nodes


def _collect_handlers(
        contexts: List[ModuleContext]
) -> Dict[str, List[Tuple[str, int]]]:
    handlers: Dict[str, List[Tuple[str, int]]] = {}
    for ctx in contexts:
        for node in _walked(ctx):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name.startswith("handle_"):
                handlers.setdefault(node.name[len("handle_"):], []).append(
                    (ctx.path, node.lineno))
    return handlers


def _collect_client_calls(
        contexts: List[ModuleContext]
) -> List[Tuple[str, str, int]]:
    """(method, path, line) for every literal-method RPC call site:
    ``conn.call("m", ...)``, ``pool.call(addr, "m", ...)``,
    ``conn.start_call("m", ...)``, ``call_with_retry(get_conn, "m")``."""
    calls: List[Tuple[str, str, int]] = []
    for ctx in contexts:
        for node in _walked(ctx):
            if not isinstance(node, ast.Call):
                continue
            method: Optional[str] = None
            if isinstance(node.func, ast.Attribute):
                if node.func.attr == "call":
                    method = _str_arg(node, 0) or _str_arg(node, 1)
                elif node.func.attr == "start_call":
                    method = _str_arg(node, 0)
            d = _dotted(node.func)
            if d is not None and d.split(".")[-1] == "call_with_retry":
                method = _str_arg(node, 1)
            if method is not None:
                calls.append((method, ctx.path, node.lineno))
    return calls


def check_rpc_conformance(contexts: List[ModuleContext],
                          cfg: ProjectConfig) -> List[Finding]:
    rule = "rpc-conformance"
    findings: List[Finding] = []
    schemas = _collect_schemas(cfg)
    idempotent, idem_line = _collect_idempotent(cfg)
    # registry questions ("does a handler exist?") consult the whole
    # tree — via the summary index, which serves unchanged modules from
    # the on-disk cache instead of re-parsing them — while findings are
    # only emitted for the scanned contexts.  Without the whole-tree
    # view, scanning one file floods false "no service defines
    # handle_X" findings (and could poison the baseline via
    # ``--update-baseline``).
    from ray_tpu.tools.check.ipa import index_for
    handlers_all = index_for(contexts, cfg).all_handlers()
    handlers = _collect_handlers(contexts)
    core_files = set(cfg.core_service_files)

    for method, path, line in _collect_client_calls(contexts):
        if method.startswith("_"):
            continue  # internal pseudo-methods (e.g. _protocol rejects)
        if method not in handlers_all:
            findings.append(Finding(
                path=path, line=line, rule=rule, symbol=method,
                message=f"client calls method {method!r} but no service "
                        f"defines handle_{method}"))

    for method in sorted(idempotent):
        if method not in handlers_all:
            findings.append(Finding(
                path=cfg.rpc_path, line=idem_line, rule=rule,
                symbol=f"idempotent.{method}",
                message=f"IDEMPOTENT_METHODS lists {method!r} but no "
                        f"service defines handle_{method} (stale entry "
                        f"licensing retry-after-send for nothing)"))

    for method, sites in sorted(handlers.items()):
        for path, line in sites:
            if path in core_files and method not in schemas:
                findings.append(Finding(
                    path=path, line=line, rule=rule,
                    symbol=f"schema.{method}",
                    message=f"control-plane handler handle_{method} has "
                            f"no messages.py schema: payloads cross "
                            f"processes unvalidated (register_schema"
                            f"({method!r}, ...))"))
    return findings


# ---------------------------------------------------------------------------
# failpoint-registry
# ---------------------------------------------------------------------------

def _failpoint_name(call: ast.Call) -> Optional[str]:
    """Literal site name, with f-string holes normalized to ``<expr>``
    (``f"rpc.{method}.reply_drop"`` -> ``rpc.<method>.reply_drop`` —
    the exact spelling the doc's generic-site table uses)."""
    if not call.args:
        return None
    arg = call.args[0]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
        return arg.value
    if isinstance(arg, ast.JoinedStr):
        parts: List[str] = []
        for v in arg.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                try:
                    parts.append(f"<{ast.unparse(v.value)}>")
                except Exception:  # pragma: no cover - unparse fallback
                    parts.append("<expr>")
        return "".join(parts)
    return None


def check_failpoint_registry(contexts: List[ModuleContext],
                             cfg: ProjectConfig) -> List[Finding]:
    rule = "failpoint-registry"
    findings: List[Finding] = []
    doc = cfg.read(cfg.failpoint_doc) or ""
    # exact-match against backtick-quoted names: a plain substring test
    # would let `raylet.spill` ride on a documented `raylet.spill.fail`.
    # Single-line matches only, else ``` fences swallow whole sections.
    documented = set(re.findall(r"`([^`\n]+)`", doc))
    sites: Dict[str, List[Tuple[str, int]]] = {}
    for ctx in contexts:
        for node in _walked(ctx):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or d.split(".")[-1] not in ("failpoint",
                                                     "afailpoint"):
                continue
            name = _failpoint_name(node)
            if name is not None:
                sites.setdefault(name, []).append((ctx.path, node.lineno))
    for name, locs in sorted(sites.items()):
        if len(locs) > 1:
            first = f"{locs[0][0]}:{locs[0][1]}"
            for path, line in locs[1:]:
                findings.append(Finding(
                    path=path, line=line, rule=rule,
                    symbol=f"dup.{name}",
                    message=f"failpoint site {name!r} already defined at "
                            f"{first}: arming it fires both sites"))
        if name not in documented:
            path, line = locs[0]
            findings.append(Finding(
                path=path, line=line, rule=rule, symbol=f"doc.{name}",
                message=f"failpoint site {name!r} not documented in "
                        f"{cfg.failpoint_doc} (add it to the woven-sites "
                        f"table)"))
    return findings


# ---------------------------------------------------------------------------
# flight-vocab
# ---------------------------------------------------------------------------

def _collect_flight_vocab(cfg: ProjectConfig) -> Set[str]:
    """Keys of the ``EVENT_TYPES`` catalogue in the flight-recorder
    module — parsed statically, same discipline as the schema and
    idempotent-method registries."""
    src = cfg.read(cfg.flight_module)
    if src is None:
        return set()
    for node in ast.walk(ast.parse(src)):
        target = None
        if isinstance(node, ast.Assign) and node.targets:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if isinstance(target, ast.Name) and target.id == "EVENT_TYPES" \
                and isinstance(getattr(node, "value", None), ast.Dict):
            return {k.value for k in node.value.keys
                    if isinstance(k, ast.Constant)
                    and isinstance(k.value, str)}
    return set()


def check_flight_vocab(contexts: List[ModuleContext],
                       cfg: ProjectConfig) -> List[Finding]:
    """Every literal event type passed to a flight-recorder ``record``
    call must be declared in the ``EVENT_TYPES`` catalogue (the same
    contract the failpoint registry enforces for site names).  At
    runtime an undeclared type silently degrades to ``mark``; this
    rule turns that degradation into a CI failure so the postmortem
    renderer's legend stays the single complete vocabulary."""
    rule = "flight-vocab"
    findings: List[Finding] = []
    vocab = _collect_flight_vocab(cfg)
    if not vocab:
        return findings  # recorder module outside this tree
    for ctx in contexts:
        in_module = ctx.path == cfg.flight_module
        for node in _walked(ctx):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or not d.endswith(".record"):
                continue
            recv = d.rsplit(".", 1)[0]
            # `_flight.record(...)` everywhere; inside the recorder
            # module the instance calls (`r.record`, `rec.record`,
            # `self.record`) are in scope too
            if "flight" not in recv \
                    and not (in_module and recv in ("r", "rec", "self")):
                continue
            etype = _str_arg(node, 0)
            if etype is not None and etype not in vocab:
                findings.append(Finding(
                    path=ctx.path, line=node.lineno, rule=rule,
                    symbol=etype,
                    message=f"flight event type {etype!r} is not "
                            f"declared in EVENT_TYPES "
                            f"({cfg.flight_module}): at runtime it "
                            f"degrades to 'mark' and the postmortem "
                            f"legend loses it — declare it in the "
                            f"catalogue"))
    return findings


# ---------------------------------------------------------------------------
# trace-propagation
# ---------------------------------------------------------------------------

#: payload dict keys that carry the trace chain: an explicit ``trace``
#: carrier, or a pickled TaskSpec (whose ``trace_context`` field is it)
_TRACE_PAYLOAD_KEYS = {"trace", "spec_blob", "specs_blob"}

#: telemetry/infra methods that legitimately carry no request context
#: (their payloads are aggregates of many requests, not one chain)
_TRACE_EXEMPT_METHODS = {
    "clock_sync", "report_metrics", "report_spans", "report_trace_spans",
    "report_profile", "report_task_events",
}


def _call_site_payload(node: ast.Call
                       ) -> Tuple[Optional[str], Optional[ast.expr]]:
    """(literal method, payload expression) of one RPC call site, or
    (None, None) when the method isn't a string literal."""
    if isinstance(node.func, ast.Attribute):
        if node.func.attr == "call":
            m = _str_arg(node, 0)
            if m is not None:  # conn.call("m", data)
                return m, node.args[1] if len(node.args) > 1 else None
            m = _str_arg(node, 1)
            if m is not None:  # pool.call(addr, "m", data)
                return m, node.args[2] if len(node.args) > 2 else None
        elif node.func.attr == "start_call":
            m = _str_arg(node, 0)
            if m is not None:
                return m, node.args[1] if len(node.args) > 1 else None
    d = _dotted(node.func)
    if d is not None and d.split(".")[-1] == "call_with_retry":
        m = _str_arg(node, 1)
        if m is not None:  # call_with_retry(get_conn, "m", data)
            return m, node.args[2] if len(node.args) > 2 else None
    return None, None


def check_trace_propagation(contexts: List[ModuleContext],
                            cfg: ProjectConfig) -> List[Finding]:
    """Every RPC call site on the serve request path (all of
    ``serve/``) and in the worker's submit-path functions must forward
    the trace context: a payload dict literal carrying ``trace`` or a
    spec blob (``TaskSpec.trace_context`` rides inside).  A site that
    cannot is one more RPC hop where the chain silently breaks — the
    assembled trace then loses everything downstream of it.  Suppress
    deliberate exceptions with ``# rtpu-check: disable=trace-propagation``."""
    rule = "trace-propagation"
    findings: List[Finding] = []
    # a call inside a nested def is reached by the walk of BOTH the
    # outer and the inner function — report each site once
    seen_sites: set = set()
    worker_funcs = set(cfg.trace_worker_funcs)
    for ctx in contexts:
        in_serve = any(ctx.path.startswith(p)
                       for p in cfg.trace_scope_dirs)
        is_worker = ctx.path == cfg.trace_worker_file
        if not in_serve and not is_worker:
            continue
        for fnode in _walked(ctx):
            if not isinstance(fnode, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                continue
            if is_worker and fnode.name not in worker_funcs:
                continue
            # name -> dict-literal assignment (payload built above the
            # call: ``payload = {...}; conn.call("m", payload)``)
            dict_assigns: Dict[str, ast.Dict] = {}
            for n in ast.walk(fnode):
                if isinstance(n, ast.Assign) \
                        and isinstance(n.value, ast.Dict):
                    for t in n.targets:
                        if isinstance(t, ast.Name):
                            dict_assigns[t.id] = n.value
            for n in ast.walk(fnode):
                if not isinstance(n, ast.Call):
                    continue
                method, payload = _call_site_payload(n)
                if method is None or method.startswith("_") \
                        or method in _TRACE_EXEMPT_METHODS:
                    continue
                resolved = payload
                if isinstance(resolved, ast.Name):
                    resolved = dict_assigns.get(resolved.id)
                ok = False
                if isinstance(resolved, ast.Dict):
                    keys = {k.value for k in resolved.keys
                            if isinstance(k, ast.Constant)}
                    ok = bool(keys & _TRACE_PAYLOAD_KEYS)
                if not ok:
                    site = (ctx.path, n.lineno, n.col_offset, method)
                    if site in seen_sites:
                        continue
                    seen_sites.add(site)
                    findings.append(Finding(
                        path=ctx.path, line=n.lineno, rule=rule,
                        symbol=method,
                        message=f"RPC call {method!r} on the traced "
                                f"request path does not forward the "
                                f"trace context (payload needs a "
                                f"'trace' key or a spec blob; or "
                                f"suppress with # rtpu-check: "
                                f"disable={rule})"))
    return findings


# ---------------------------------------------------------------------------
# persist-conformance
# ---------------------------------------------------------------------------

#: method names whose call on a table attribute mutates it
_MUTATING_METHODS = {
    "pop", "popitem", "setdefault", "update", "clear", "append",
    "extend", "insert", "add", "discard", "remove",
}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.<attr>`` -> attr (one level only)."""
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _table_of_target(node: ast.AST, tables: Set[str]) -> Optional[str]:
    """The persisted table a store/del target touches:
    ``self.kv[...] = / del self.actors[...] / self.job_counter += 1``."""
    # unwrap one subscript layer: self.kv[ns][k] = v roots at self.kv
    while isinstance(node, ast.Subscript):
        node = node.value
    attr = _self_attr(node)
    return attr if attr in tables else None


class _PersistVisitor(ast.NodeVisitor):
    """Per-function facts for the conformance fixed point: which
    persisted tables it mutates directly, whether it calls a persist
    entry point, and which same-class helpers it invokes."""

    def __init__(self, tables: Set[str], persist_calls: Set[str]):
        self.tables = tables
        self.persist_calls = persist_calls
        self.mutates: Set[str] = set()
        self.persists = False
        self.calls: Set[str] = set()

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            tbl = _table_of_target(t, self.tables)
            if tbl is not None:
                self.mutates.add(tbl)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        tbl = _table_of_target(node.target, self.tables)
        if tbl is not None:
            self.mutates.add(tbl)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            tbl = _table_of_target(t, self.tables)
            if tbl is not None:
                self.mutates.add(tbl)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            recv = node.func.value
            attr = node.func.attr
            if attr in self.persist_calls and (
                    _self_attr(recv) is not None
                    or isinstance(recv, ast.Name)):
                # self._schedule_persist() / self.wal.append-style
                # helpers — receiver shape is deliberately loose: the
                # names are project-specific enough not to collide
                self.persists = True
            tbl = _self_attr(recv)
            if tbl in self.tables and attr in _MUTATING_METHODS:
                self.mutates.add(tbl)
            if isinstance(recv, ast.Name) and recv.id == "self":
                self.calls.add(attr)  # self.<helper>(...)
        self.generic_visit(node)


def check_persist_conformance(contexts: List[ModuleContext],
                              cfg: ProjectConfig) -> List[Finding]:
    """Every ``handle_*`` coroutine of the GCS service that mutates a
    persisted table — directly or through a helper it calls — must
    reach the durable tier (a WAL append / flush or the snapshot
    scheduler) on the same call graph.  A handler that doesn't acks a
    mutation the next head restart silently forgets."""
    rule = "persist-conformance"
    findings: List[Finding] = []
    ctx = next((c for c in contexts
                if c.path == cfg.persist_service_file), None)
    if ctx is None:
        return findings  # service file outside this scan's scope
    tables = set(cfg.persist_tables)
    persist_calls = set(cfg.persist_calls)
    facts: Dict[str, _PersistVisitor] = {}
    lines: Dict[str, int] = {}
    for node in _walked(ctx):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            v = _PersistVisitor(tables, persist_calls)
            for stmt in node.body:
                v.visit(stmt)
            facts[node.name] = v
            lines.setdefault(node.name, node.lineno)

    def _closure(seed: Set[str]) -> Set[str]:
        """Methods in ``seed`` plus every method that (transitively)
        calls one of them."""
        out = set(seed)
        changed = True
        while changed:
            changed = False
            for name, v in facts.items():
                if name not in out and v.calls & out:
                    out.add(name)
                    changed = True
        return out

    mutating = _closure({n for n, v in facts.items() if v.mutates})
    persisting = _closure({n for n, v in facts.items() if v.persists})
    for name in sorted(facts):
        if not name.startswith("handle_"):
            continue
        if name in mutating and name not in persisting:
            direct = facts[name].mutates
            via = sorted(facts[name].calls & mutating)
            what = ", ".join(sorted(direct)) if direct else \
                f"via {', '.join(via)}"
            findings.append(Finding(
                path=ctx.path, line=lines[name], rule=rule,
                symbol=name,
                message=f"GCS handler {name} mutates persisted "
                        f"table(s) ({what}) without appending to the "
                        f"WAL / scheduling a snapshot: the acked "
                        f"mutation is lost on the next head restart "
                        f"(call self._wal_append/_wal_flush or "
                        f"self._schedule_persist)"))
    return findings


# ---------------------------------------------------------------------------
# metric-drift
# ---------------------------------------------------------------------------

_METRIC_FACTORIES = {"Counter", "Gauge", "Histogram",
                     "_counter", "_gauge", "_hist", "set_gauge"}


def collect_metric_names(
        contexts: List[ModuleContext]
) -> Dict[str, List[Tuple[str, int]]]:
    """``ray_tpu_*`` series name -> construction sites.  Shared with
    ``scripts/metrics_smoke.py --update`` so the regenerated golden
    catalogue is exactly the set of names the code constructs."""
    names: Dict[str, List[Tuple[str, int]]] = {}
    for ctx in contexts:
        for node in _walked(ctx):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or d.split(".")[-1] not in _METRIC_FACTORIES:
                continue
            name = _str_arg(node, 0)
            if name is None:
                # constructors accept the name as a keyword too
                for kw in node.keywords:
                    if kw.arg == "name" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        name = kw.value.value
            if name is None or not name.startswith("ray_tpu_"):
                continue
            names.setdefault(name, []).append((ctx.path, node.lineno))
    return names


#: rule constructors whose string kwargs reference metric series:
#: RecordingRule reads a raw series (``source``) and DEFINES a derived
#: signal (``name``); AlertRule reads either (``signal`` / ``source``)
_RULE_CONSTRUCTORS = {"RecordingRule", "AlertRule"}


def _collect_rule_series_refs(
        contexts: List[ModuleContext]
) -> Tuple[List[Tuple[str, str, str, int]], Set[str]]:
    """(kwarg, series, path, line) for every literal series referenced
    by a RecordingRule/AlertRule constructor, plus the set of derived
    signal names those RecordingRule calls define."""
    refs: List[Tuple[str, str, str, int]] = []
    defined: Set[str] = set()
    for ctx in contexts:
        for node in _walked(ctx):
            if not isinstance(node, ast.Call):
                continue
            d = _dotted(node.func)
            if d is None or d.split(".")[-1] not in _RULE_CONSTRUCTORS:
                continue
            ctor = d.split(".")[-1]
            kwargs = {kw.arg: kw.value.value for kw in node.keywords
                      if kw.arg and isinstance(kw.value, ast.Constant)
                      and isinstance(kw.value.value, str)}
            if ctor == "RecordingRule" and kwargs.get("name"):
                defined.add(kwargs["name"])
            for key in ("source", "signal"):
                val = kwargs.get(key)
                if val:
                    refs.append((key, val, ctx.path, node.lineno))
    return refs, defined


def check_metric_drift(contexts: List[ModuleContext],
                       cfg: ProjectConfig) -> List[Finding]:
    rule = "metric-drift"
    findings: List[Finding] = []
    golden_src = cfg.read(cfg.metrics_golden)
    golden = parse_catalogue(golden_src) if golden_src is not None else set()
    for name, sites in sorted(collect_metric_names(contexts).items()):
        if name in golden:
            continue
        for path, line in sites:
            findings.append(Finding(
                path=path, line=line, rule=rule,
                symbol=name,
                message=f"metric {name!r} is not in "
                        f"{cfg.metrics_golden}: dashboards and the "
                        f"metrics smoke test won't see it (add it, "
                        f"or run scripts/metrics_smoke.py --update)"))
    # recording/alert rules must reference series that exist: a raw
    # ``ray_tpu_*`` reference must be in the golden catalogue, and a
    # derived-signal reference must be defined by some RecordingRule
    # (resolved against the whole tree, so path-restricted scans don't
    # flood false unknown-signal findings)
    refs, defined_all = _collect_rule_series_refs(contexts)
    if any(not series.startswith("ray_tpu_")
           and series not in defined_all
           for _kwarg, series, _path, _line in refs):
        # a derived-signal ref the scanned files don't define: resolve
        # against the whole tree before flagging (path-restricted runs
        # must not flood false unknown-signal findings) — the index
        # serves this from cached summaries, no reparse
        from ray_tpu.tools.check.ipa import index_for
        defined_all = defined_all | index_for(contexts, cfg).all_signals()
    for kwarg, series, path, line in refs:
        if series.startswith("ray_tpu_"):
            if series not in golden:
                findings.append(Finding(
                    path=path, line=line, rule=rule,
                    symbol=f"rule.{series}",
                    message=f"rule {kwarg}={series!r} references a "
                            f"series missing from {cfg.metrics_golden}"
                            f": the rule would evaluate a series no "
                            f"producer constructs"))
        elif series not in defined_all:
            findings.append(Finding(
                path=path, line=line, rule=rule,
                symbol=f"rule.{series}",
                message=f"rule {kwarg}={series!r} references a derived "
                        f"signal no RecordingRule defines"))
    return findings


# ---------------------------------------------------------------------------
# step-instrumentation
# ---------------------------------------------------------------------------

def check_step_instrumentation(contexts: List[ModuleContext],
                               cfg: ProjectConfig) -> List[Finding]:
    """An engine class exposing a compiled step entry point (``step``,
    ``shard_step``, ``decode_step``, ``train_step``,
    ``compute_actions``) must route every ``jax.jit`` it binds to an
    attribute through the device-telemetry wrapper
    (``device_telemetry.instrument_step``).  An unwrapped jit is a
    blind spot: its compiles never reach
    ``ray_tpu_xla_compiles_total``, so a recompile storm in that engine
    is invisible to the RecompileStorm alert."""
    rule = "step-instrumentation"
    findings: List[Finding] = []

    def _is_jit_call(node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        d = _dotted(node.func)
        if d is None:
            return False
        # catch local aliases too: `from jax import jit as _jit`, pjit
        return d.split(".")[-1].lstrip("_") in ("jit", "pjit")

    def _is_wrapped(value: ast.AST) -> bool:
        if not isinstance(value, ast.Call):
            return False
        d = _dotted(value.func)
        return d is not None and \
            d.split(".")[-1] in cfg.device_wrapper_names

    for ctx in contexts:
        for cls in _walked(ctx):
            if not isinstance(cls, ast.ClassDef):
                continue
            methods = {n.name for n in cls.body
                       if isinstance(n, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))}
            if not methods & set(cfg.step_entry_points):
                continue
            for node in ast.walk(cls):
                if not isinstance(node, ast.Assign):
                    continue
                attr_targets = [
                    t for t in node.targets
                    if isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"]
                if not attr_targets:
                    continue
                if _is_wrapped(node.value):
                    continue
                if not any(_is_jit_call(n)
                           for n in ast.walk(node.value)):
                    continue
                attr = attr_targets[0].attr
                findings.append(Finding(
                    path=ctx.path, line=node.lineno, rule=rule,
                    symbol=f"{cls.name}.{attr}",
                    message=f"{cls.name} binds self.{attr} to a "
                            f"jax.jit without device_telemetry."
                            f"instrument_step: its compiles are "
                            f"invisible to the device plane (wrap the "
                            f"jit, or suppress if this callable never "
                            f"serves a step entry point)"))
    return findings


#: rule name -> cross-file checker
PROJECT_RULES = {
    "rpc-conformance": check_rpc_conformance,
    "failpoint-registry": check_failpoint_registry,
    "metric-drift": check_metric_drift,
    "trace-propagation": check_trace_propagation,
    "persist-conformance": check_persist_conformance,
    "step-instrumentation": check_step_instrumentation,
    "flight-vocab": check_flight_vocab,
}
