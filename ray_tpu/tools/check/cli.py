"""rtpu-check command line: discover files, run rules, report.

Usage::

    python -m ray_tpu.tools.check [paths...]      # default: ray_tpu/
    python -m ray_tpu.tools.check --list-rules
    python -m ray_tpu.tools.check --select async-blocking,metric-drift
    python -m ray_tpu.tools.check --update-baseline
    python -m ray_tpu.tools.check --changed-only  # pre-commit speed
    python -m ray_tpu.tools.check --json          # machine-readable

Exit status: 0 clean (every finding suppressed inline or baselined),
1 when new findings exist, 2 on usage/internal error.  Findings print
as ``file:line rule message`` so CI output is click-through-able.

The interprocedural rules (and the whole-tree registries the older
cross-file rules consult) run off per-module summaries cached under
``build/rtpu-check-summaries.pkl``, keyed by file content hash — a
warm run re-summarizes only edited modules.  ``--changed-only``
narrows the *scan scope* to git-changed files plus their direct
importers — the importers ride along for the cross-file rules only
(per-file rule output on an unchanged file cannot change); the
registries still see the whole tree through the cache, so a scoped
run reports the same truths as a full one, just only for the files
you touched.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
from typing import Iterable, List, Optional, Set

from ray_tpu.tools.check.astrules import ASYNC_RULES, ModuleContext, \
    parse_module
from ray_tpu.tools.check.findings import Finding, Suppressions, \
    load_baseline, merge_baseline, split_new_findings
from ray_tpu.tools.check.ipa import SummaryCache, default_cache_path, \
    index_for
from ray_tpu.tools.check.iparules import IPA_RULES
from ray_tpu.tools.check.project import PROJECT_RULES, ProjectConfig

ALL_RULES = {**ASYNC_RULES, **PROJECT_RULES, **IPA_RULES}

#: default baseline location (checked in; starts empty)
BASELINE_REL = os.path.join("ray_tpu", "tools", "check", "baseline.txt")


def _repo_root() -> str:
    """The directory that holds the ``ray_tpu`` package this module was
    imported from — works from any cwd."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))))


def discover_files(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    seen: set = set()

    def _add(fn: str) -> None:
        # dedupe across overlapping path args (`ray_tpu ray_tpu/x.py`):
        # a double-parsed file doubles per-file findings and makes
        # failpoint-registry call every site a duplicate of itself
        key = os.path.abspath(fn)
        if key not in seen:
            seen.add(key)
            out.append(fn)

    for p in paths:
        if os.path.isfile(p):
            _add(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        _add(os.path.join(dirpath, f))
        else:
            raise FileNotFoundError(p)
    return out


def parse_files(files: Iterable[str], root: str) -> List[ModuleContext]:
    contexts: List[ModuleContext] = []
    for fn in files:
        with open(fn, encoding="utf-8") as f:
            source = f.read()
        rel = os.path.relpath(os.path.abspath(fn), root).replace(os.sep, "/")
        contexts.append(parse_module(rel, source))
    return contexts


def run_rules(contexts: List[ModuleContext], cfg: ProjectConfig,
              select: Optional[Iterable[str]] = None, *,
              per_file_scope: Optional[Set[str]] = None) -> List[Finding]:
    """Run the selected rules (default: all) and drop findings covered
    by an inline ``# rtpu-check: disable=`` comment.

    ``per_file_scope`` (repo-relative paths) narrows the *per-file*
    rules to those contexts only; cross-file and interprocedural rules
    always see every context.  A per-file rule's findings depend only
    on that one file's source, so skipping it on an unchanged dependent
    can never hide a finding the edit introduced — this is what keeps
    ``--changed-only`` sub-second on a one-file edit."""
    selected = set(select) if select is not None else set(ALL_RULES)
    unknown = selected - set(ALL_RULES)
    if unknown:
        raise ValueError(f"unknown rule(s): {', '.join(sorted(unknown))}")
    findings: List[Finding] = []
    for name, rule in ASYNC_RULES.items():
        if name in selected:
            for ctx in contexts:
                if per_file_scope is not None \
                        and ctx.path not in per_file_scope:
                    continue
                findings.extend(rule(ctx))
    for name, rule in {**PROJECT_RULES, **IPA_RULES}.items():
        if name in selected:
            findings.extend(rule(contexts, cfg))
    by_path = {ctx.path: ctx.suppressions for ctx in contexts}

    def suppressions_for(path: str) -> Suppressions:
        # cross-file rules can anchor findings at registry files (e.g.
        # rpc.py's IDEMPOTENT_METHODS) outside the scan scope; their
        # inline markers must still count, else the same tree passes or
        # fails depending on which paths were passed
        if path not in by_path:
            by_path[path] = Suppressions(cfg.read(path) or "")
        return by_path[path]

    kept = [f for f in findings
            if not suppressions_for(f.path).covers(f.line, f.rule)]
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.symbol))
    return kept


def changed_files(root: str) -> List[str]:
    """Repo-relative ``ray_tpu/**.py`` paths touched in the working
    tree (unstaged + staged + untracked), for ``--changed-only``."""
    out: Set[str] = set()
    for cmd in (["git", "diff", "--name-only", "HEAD"],
                ["git", "ls-files", "--others", "--exclude-standard"]):
        try:
            proc = subprocess.run(cmd, cwd=root, capture_output=True,
                                  text=True, timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if proc.returncode != 0:
            continue
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("ray_tpu/") and line.endswith(".py"):
                out.add(line)
    return sorted(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="rtpu-check",
        description="runtime-invariant static analysis for ray_tpu")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: ray_tpu/)")
    ap.add_argument("--root", default=None,
                    help="repo root (default: auto-detected)")
    ap.add_argument("--select", default=None, metavar="RULE[,RULE...]",
                    help="run only these rules")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: <root>/{BASELINE_REL})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report baselined findings too")
    ap.add_argument("--update-baseline", action="store_true",
                    help="update the baseline from current findings "
                         "(out-of-scope entries and '# why' comments "
                         "are preserved)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as a JSON document on stdout")
    ap.add_argument("--changed-only", action="store_true",
                    help="scan only git-changed files plus their "
                         "direct importers (pre-commit mode)")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore and do not write the summary cache")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(ALL_RULES):
            kind = "per-file" if name in ASYNC_RULES else (
                "interprocedural" if name in IPA_RULES else "cross-file")
            print(f"{name:24s} [{kind}]")
        return 0

    root = os.path.abspath(args.root or _repo_root())
    baseline_path = args.baseline or os.path.join(root, BASELINE_REL)
    select = ([r.strip() for r in args.select.split(",") if r.strip()]
              if args.select else None)
    cache = None if args.no_cache else SummaryCache(
        default_cache_path(root))
    cfg = ProjectConfig(root=root)
    try:
        # one index per run: the interprocedural rules, the whole-tree
        # registries, and --changed-only dependent resolution all read
        # from it (warm modules come straight from the summary cache)
        index = index_for([], cfg, cache=cache)
        per_file_scope = None
        if args.changed_only:
            changed = [p for p in changed_files(root)
                       if os.path.isfile(os.path.join(root, p))]
            scope = set(changed) | index.dependents(changed)
            # dependents ride along for the cross-file rules only; the
            # per-file rules re-run just on the files actually edited
            per_file_scope = set(changed)
            paths = sorted(os.path.join(root, p) for p in scope
                           if os.path.isfile(os.path.join(root, p)))
            if not paths:
                if cache is not None:
                    cache.save()
                if args.as_json:
                    print(json.dumps({"findings": [], "files": 0,
                                      "baselined": 0}))
                else:
                    print("rtpu-check: clean (0 changed files)")
                return 0
        else:
            paths = args.paths or [os.path.join(root, "ray_tpu")]
        files = discover_files(paths)
        contexts = parse_files(files, root)
        findings = run_rules(contexts, cfg, select,
                             per_file_scope=per_file_scope)
    except (FileNotFoundError, SyntaxError, ValueError) as e:
        print(f"rtpu-check: error: {e}", file=sys.stderr)
        return 2
    finally:
        if cache is not None:
            cache.save()

    if args.update_baseline:
        content = merge_baseline(
            baseline_path, findings,
            scanned_paths={ctx.path for ctx in contexts},
            selected_rules=set(select) if select else set(ALL_RULES))
        with open(baseline_path, "w") as f:
            f.write(content)
        n_keys = sum(1 for ln in content.splitlines()
                     if ln and not ln.startswith("#"))
        print(f"rtpu-check: wrote {n_keys} key(s) to {baseline_path}")
        return 0

    baseline = set() if args.no_baseline else load_baseline(baseline_path)
    new, baselined = split_new_findings(findings, baseline)
    n_files = len(files)
    if args.as_json:
        print(json.dumps({
            "findings": [{"path": f.path, "line": f.line,
                          "rule": f.rule, "symbol": f.symbol,
                          "message": f.message, "key": f.key}
                         for f in new],
            "files": n_files, "baselined": len(baselined)},
            indent=2, sort_keys=True))
        return 1 if new else 0
    for f in new:
        print(f.render())
    if new:
        print(f"rtpu-check: {len(new)} finding(s) in {n_files} file(s)"
              + (f" (+{len(baselined)} baselined)" if baselined else ""),
              file=sys.stderr)
        return 1
    print(f"rtpu-check: clean ({n_files} files"
          + (f", {len(baselined)} baselined finding(s)" if baselined
             else "") + ")")
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
