"""rtpu-check: runtime-invariant static analysis for the ray_tpu tree.

The reference runtime leans on protobuf codegen, C++ type checking, and
tsan/asan CI to keep its control plane honest.  This reproduction's
control plane is dynamic Python on asyncio, so its invariants — never
block the event loop, never ``await`` under a thread lock, never swallow
cancellation, keep the RPC/failpoint/metric registries in agreement with
the code — are enforced here instead, by a small AST analyzer with
project-specific rules.

Entry points::

    python -m ray_tpu.tools.check      # or: make check

Programmatic: :func:`ray_tpu.tools.check.cli.run_rules` over parsed
:class:`~ray_tpu.tools.check.astrules.ModuleContext` objects.  Rule
catalogue and workflow: ``docs/static_analysis.md``.
"""

from ray_tpu.tools.check.astrules import (  # noqa: F401
    ASYNC_RULES, ModuleContext, check_async_blocking,
    check_await_under_lock, check_cancellation_swallow, parse_module,
)
from ray_tpu.tools.check.cli import (  # noqa: F401
    ALL_RULES, discover_files, main, parse_files, run_rules,
)
from ray_tpu.tools.check.findings import (  # noqa: F401
    Finding, Suppressions, format_baseline, load_baseline,
    load_baseline_comments, merge_baseline, split_new_findings,
)
from ray_tpu.tools.check.project import (  # noqa: F401
    PROJECT_RULES, ProjectConfig, check_failpoint_registry,
    check_metric_drift, check_rpc_conformance,
)
