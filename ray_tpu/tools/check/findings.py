"""Core types for rtpu-check: findings, suppressions, the baseline file.

A **finding** is one rule violation at one source location.  Its ``key``
deliberately excludes the line number — ``path::rule::symbol`` — so a
baseline entry survives unrelated edits that shift lines.  ``symbol`` is
whatever stable token the rule anchors on (the blocked call's dotted
name, the RPC method, the metric name, ...).

Two escape hatches keep the tree at zero *unsuppressed* findings without
forcing a fix-everything flag day:

* **Inline suppression** — ``# rtpu-check: disable=<rule>[,<rule>...]``
  either trailing the flagged line or on a standalone comment line
  directly above it.  Use for violations that are *correct by local
  argument* (say why in the surrounding comment).
* **Baseline** — a checked-in file of finding keys
  (``ray_tpu/tools/check/baseline.txt``); entries are debt, each line
  carries a justification after ``#``.  ``--update-baseline`` refreshes
  it from the current run, preserving justifications and any entries
  the run's scope (paths / ``--select``) could not have re-observed.
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Set, Tuple

__all__ = [
    "Finding", "Suppressions", "parse_catalogue", "load_baseline",
    "load_baseline_comments", "format_baseline", "merge_baseline",
    "split_new_findings",
]


@dataclass(frozen=True)
class Finding:
    path: str       # repo-root-relative, '/'-separated
    line: int       # 1-based
    rule: str
    message: str
    symbol: str     # stable token for the baseline key

    @property
    def key(self) -> str:
        return f"{self.path}::{self.rule}::{self.symbol}"

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


_SUPPRESS_RE = re.compile(
    r"#\s*rtpu-check:\s*disable=([A-Za-z0-9_,\- ]+)")


class Suppressions:
    """Per-file map of line -> suppressed rule names.

    A ``# rtpu-check: disable=r1,r2`` comment suppresses its own line;
    when the comment is the whole line (nothing but whitespace before
    the ``#``), it also suppresses the next line — so multi-line
    statements can carry the marker directly above their first line.
    """

    def __init__(self, source: str):
        self._by_line: Dict[int, Set[str]] = {}
        for lineno, text in enumerate(source.splitlines(), start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(1).split(",") if r.strip()}
            self._by_line.setdefault(lineno, set()).update(rules)
            if text[:m.start()].strip() in ("", "#"):
                # standalone comment: covers the following line too
                self._by_line.setdefault(lineno + 1, set()).update(rules)

    def covers(self, line: int, rule: str) -> bool:
        return rule in self._by_line.get(line, ())

    def __bool__(self) -> bool:
        return bool(self._by_line)


def parse_catalogue(text: str) -> Set[str]:
    """Entries of a one-name-per-line file where ``#`` starts a comment
    anywhere — the single grammar for baseline and golden-catalogue
    files (also used by ``scripts/metrics_smoke.py``)."""
    out: Set[str] = set()
    for raw in text.splitlines():
        entry = raw.split("#", 1)[0].strip()
        if entry:
            out.add(entry)
    return out


def load_baseline(path: str) -> Set[str]:
    """Read finding keys from a baseline file.  Missing file == empty
    baseline."""
    try:
        with open(path) as f:
            return parse_catalogue(f.read())
    except FileNotFoundError:
        return set()


def load_baseline_comments(path: str) -> Dict[str, str]:
    """key -> its trailing ``# why`` justification, so a baseline
    rewrite keeps the hand-written rationale for keys that survive."""
    comments: Dict[str, str] = {}
    try:
        with open(path) as f:
            for raw in f:
                entry, sep, comment = raw.partition("#")
                key = entry.strip()
                if key and sep and comment.strip():
                    comments[key] = comment.strip()
    except FileNotFoundError:
        pass
    return comments


def format_baseline(keys: Iterable[str],
                    comments: Optional[Dict[str, str]] = None) -> str:
    header = (
        "# rtpu-check baseline: known findings tolerated in this tree.\n"
        "# One key per line (path::rule::symbol); document WHY after '#'.\n"
        "# Regenerate: python -m ray_tpu.tools.check --update-baseline\n")
    lines = []
    for k in sorted(set(keys)):
        why = (comments or {}).get(k)
        lines.append(k + (f"  # {why}" if why else "") + "\n")
    return header + "".join(lines)


def merge_baseline(existing_path: str, findings: Iterable[Finding],
                   scanned_paths: Set[str],
                   selected_rules: Set[str]) -> str:
    """Baseline content for ``--update-baseline``: the current run's
    finding keys plus every existing entry the run could *not* have
    re-observed (file outside the scanned paths, or rule deselected) —
    so a ``--select``/path-restricted update never silently drops
    out-of-scope debt.  Hand-written ``# why`` justifications are kept
    for keys that survive."""
    comments = load_baseline_comments(existing_path)
    keys = {f.key for f in findings}
    for key in load_baseline(existing_path):
        parts = key.split("::", 2)
        if len(parts) == 3 and (parts[0] not in scanned_paths
                                or parts[1] not in selected_rules):
            keys.add(key)
    return format_baseline(keys, comments)


def split_new_findings(
        findings: List[Finding], baseline: Set[str]
) -> Tuple[List[Finding], List[Finding]]:
    """(new, baselined) partition of ``findings`` against ``baseline``."""
    new: List[Finding] = []
    old: List[Finding] = []
    for f in findings:
        (old if f.key in baseline else new).append(f)
    return new, old
