import sys

from ray_tpu.tools.check.cli import main

sys.exit(main())
