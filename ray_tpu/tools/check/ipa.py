"""Interprocedural analysis core for rtpu-check.

PR 4's rules are per-file and syntactic; the bug classes that still
bite under chaos — deadlocks from inconsistent lock order, leaked
pages/pins/leases on exception paths, non-idempotent retried RPCs —
all require *whole-program* reasoning.  This module provides the shared
substrate the interprocedural rules (``iparules.py``) consume:

* a **module graph** over ``ray_tpu/`` with import/alias resolution
  (``from x import f as g`` call sites resolve to ``x.f``, attribute
  receivers resolve through ``self.<attr> = Ctor(...)`` bindings);
* a **call graph**: ``self._method`` dispatch within a class and its
  bases, module-level functions, aliased cross-module calls, and
  constructor-typed attribute/local receivers (``self._kv.release`` →
  ``KVPageTable.release``);
* cached **per-function summaries**: locks acquired and held across
  calls, RPC call sites (with the retry/idempotent shape), blocking
  client entry points, self-attribute writes, append/increment-style
  mutations, and path-sensitive resource-lifecycle events;
* an **on-disk summary cache** keyed by file content hash, so a warm
  full-tree run and a ``--changed-only`` pre-commit run never re-parse
  unchanged modules.

Everything here is static (AST only) and runtime-import-free, same as
the rest of the analyzer.  Summaries are deliberately self-contained
plain data (JSON round-trippable): resolution that needs only
module-local knowledge (import aliases, attribute constructor types)
happens at summarize time; resolution that needs the whole tree (base
classes in other modules, dotted targets) happens at index time.
"""

from __future__ import annotations

import ast
import hashlib
import os
import pickle
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Iterable, List, Optional, \
    Sequence, Set, Tuple

__all__ = [
    "CACHE_VERSION", "FuncSummary", "ModuleSummary", "ProjectIndex",
    "ResourceSpec", "RESOURCE_SPECS", "SummaryCache", "default_cache_path",
    "module_dotted", "summarize_module",
]

#: bump when the summary format or the extraction logic changes — a
#: version mismatch invalidates the whole cache (content hashes only
#: catch *source* edits, not analyzer edits)
CACHE_VERSION = 9

#: client-API entry points that block the calling thread on runtime
#: RPC round trips (worker → raylet/GCS).  Holding a threading lock
#: across one serializes every other thread touching that lock behind
#: a network round trip (and the arena, and possibly a spill restore).
BLOCKING_CLIENT_CALLS = {
    "ray_tpu.get", "ray_tpu.put", "ray_tpu.wait", "ray_tpu.free",
}

_LOCK_KINDS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond",
               "Semaphore": "sem", "BoundedSemaphore": "sem"}

#: list-shaped mutations that do NOT converge on replay (a retried
#: delivery double-applies); set.add/discard and keyed subscript
#: assignment converge and are deliberately absent
_BLIND_METHODS = {"append", "extend", "insert"}


# ---------------------------------------------------------------------------
# resource-lifecycle specs
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class ResourceSpec:
    """One acquire/release pairing checked path-sensitively.

    ``acquire_methods`` match ``<recv>.<m>(...)`` where the receiver's
    trailing symbol is in ``receiver_hints`` (empty = any receiver);
    ``acquire_funcs`` match alias-resolved dotted calls (``os.open``).
    ``key_arg`` names the argument that identifies the resource (the
    release must pass a textually matching expression); ``None`` means
    the *returned value* is the token (released via
    ``value.close()``-style ``release_value_methods`` or
    ``release_funcs(value)``).

    ``checked`` acquisitions return None/False on failure — the token
    only counts as held under a truthiness guard on the result.
    ``borrows`` are callables that may take the token as an argument
    without assuming ownership (``os.fstat(fd)`` reads the fd, it does
    not adopt it); any *other* call receiving the token is treated as
    an ownership escape.  ``strict_exceptions`` additionally requires
    the held region to be exception-safe: a statement that can raise
    while the token is held and unprotected (no enclosing
    try/finally/except releasing it) is a leak on the exception edge.
    """

    name: str
    acquire_methods: Tuple[str, ...] = ()
    receiver_hints: Tuple[str, ...] = ()
    acquire_funcs: Tuple[str, ...] = ()
    release_methods: Tuple[str, ...] = ()          # <recv>.<m>(key)
    release_value_methods: Tuple[str, ...] = ()    # token.<m>()
    release_funcs: Tuple[str, ...] = ()            # f(token)
    release_all_funcs: Tuple[str, ...] = ()        # releases every token
    key_arg: Optional[int] = None
    checked: bool = False
    borrows: Tuple[str, ...] = ()
    strict_exceptions: bool = False
    #: only functions that ALSO contain a release site are checked
    #: (for pairs whose acquire is legitimately open-ended elsewhere,
    #: e.g. failpoint arm helpers that tests disarm later)
    paired_only: bool = False
    hint: str = ""


#: the project's resource pairs (docs/static_analysis.md has the
#: registration walkthrough; tests retarget the engine at fixtures)
RESOURCE_SPECS: Tuple[ResourceSpec, ...] = (
    ResourceSpec(
        name="arena-pin",
        acquire_methods=("lease", "get_pinned"),
        receiver_hints=("store",),
        release_methods=("release",),
        key_arg=0,
        checked=True,
        borrows=("len", "bytes", "memoryview"),
        strict_exceptions=True,
        hint="every store.lease()/get_pinned() pin must reach "
             "store.release(oid) on all exits (the spill sweep treats "
             "a pinned object as in-use forever)"),
    ResourceSpec(
        name="spill-fd",
        acquire_funcs=("os.open",),
        release_funcs=("os.close",),
        release_value_methods=("close",),
        checked=False,
        borrows=("os.fstat", "os.pread", "os.read", "os.lseek",
                 "os.fdopen"),
        strict_exceptions=True,
        hint="a spill/restore fd that misses its os.close on an "
             "exception edge leaks until process exit (and on some "
             "tiers holds the blob's inode live)"),
    ResourceSpec(
        name="kv-page",
        acquire_methods=("reserve",),
        receiver_hints=("_kv", "kv", "kv_table", "table"),
        release_methods=("release",),
        key_arg=0,
        checked=True,
        hint="a KV page reservation must reach the release funnel "
             "(release(request_id)) or escape into the slot table; a "
             "dropped reservation strands budget until replica "
             "restart (allocated == freed + handed_off breaks)"),
    ResourceSpec(
        name="failpoint",
        acquire_funcs=("arm",),
        release_funcs=("disarm",),
        release_all_funcs=("disarm_all", "reload_env"),
        key_arg=0,
        paired_only=True,
        strict_exceptions=True,
        hint="a function that arms AND disarms a failpoint must "
             "disarm on the exception edge too (try/finally), or a "
             "failing run leaves the site armed for every later test"),
)


def _spec_fingerprint(specs: Sequence[ResourceSpec]) -> str:
    return hashlib.sha256(repr(tuple(specs)).encode()).hexdigest()[:16]


# ---------------------------------------------------------------------------
# summaries
# ---------------------------------------------------------------------------

@dataclass
class FuncSummary:
    """One function's interprocedural facts.  All cross-references are
    module-local strings; the index resolves them globally."""

    qual: str                 # "Class.meth" or "func"
    cls: str                  # enclosing class name ("" = module level)
    name: str
    line: int
    is_async: bool = False
    #: locks this function itself acquires: (lockref, line, held-at)
    #: where lockref is "scope::sym" (scope = class name or "")
    acquires: List[Tuple[str, int, Tuple[str, ...]]] = \
        field(default_factory=list)
    #: call sites: (kind, a, b, line, locks-held) — kind/a/b encode the
    #: module-local callee reference (see _classify_call)
    calls: List[Tuple[str, str, str, int, Tuple[str, ...]]] = \
        field(default_factory=list)
    #: literal string args per call line (for wrapper-forward
    #: resolution): line -> (arg items "<idx>:<value>")
    call_lit_args: Dict[str, List[str]] = field(default_factory=dict)
    #: RPC sites: (method, kind, line, locks-held, idempotent) with
    #: kind in call|start_call|retry|client and idempotent in
    #: ""|"true"|"false" (the literal kwarg, when present)
    rpcs: List[Tuple[str, str, int, Tuple[str, ...], str]] = \
        field(default_factory=list)
    #: params (for retry-wrapper detection)
    params: Tuple[str, ...] = ()
    #: index of a param forwarded as call_with_retry's method (or -1)
    retry_forward_param: int = -1
    #: self attributes written (assign/del/subscript/mutating method)
    writes_attrs: Set[str] = field(default_factory=set)
    #: replay-divergent mutations: (attr, op, line) for blind
    #: list append/extend/insert and numeric += on self state
    blind_ops: List[Tuple[str, str, int]] = field(default_factory=list)
    #: function contains a keyed early-exit (an if whose test compares
    #: self state and whose body returns/raises) — the replay-guard
    #: shape a convergent handler uses to drop duplicate deliveries
    has_replay_guard: bool = False
    #: resource-lifecycle leak candidates found path-sensitively:
    #: (spec name, token, acquire line, leak line, kind) with kind in
    #: exit|exception|unassigned
    res_leaks: List[Tuple[str, str, int, int, str]] = \
        field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "q": self.qual, "c": self.cls, "n": self.name,
            "l": self.line, "a": int(self.is_async),
            "acq": [[r, ln, list(h)] for r, ln, h in self.acquires],
            "cal": [[k, x, y, ln, list(h)]
                    for k, x, y, ln, h in self.calls],
            "lit": self.call_lit_args,
            "rpc": [[m, k, ln, list(h), i]
                    for m, k, ln, h, i in self.rpcs],
            "par": list(self.params),
            "fwd": self.retry_forward_param,
            "wr": sorted(self.writes_attrs),
            "bl": [list(t) for t in self.blind_ops],
            "gd": int(self.has_replay_guard),
            "res": [list(t) for t in self.res_leaks],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "FuncSummary":
        return cls(
            qual=d["q"], cls=d["c"], name=d["n"], line=d["l"],
            is_async=bool(d["a"]),
            acquires=[(r, ln, tuple(h)) for r, ln, h in d["acq"]],
            calls=[(k, x, y, ln, tuple(h))
                   for k, x, y, ln, h in d["cal"]],
            call_lit_args={k: list(v) for k, v in d["lit"].items()},
            rpcs=[(m, k, ln, tuple(h), i)
                  for m, k, ln, h, i in d["rpc"]],
            params=tuple(d["par"]),
            retry_forward_param=d["fwd"],
            writes_attrs=set(d["wr"]),
            blind_ops=[tuple(t) for t in d["bl"]],  # type: ignore[misc]
            has_replay_guard=bool(d["gd"]),
            res_leaks=[tuple(t) for t in d["res"]],  # type: ignore[misc]
        )


@dataclass
class ModuleSummary:
    path: str
    sha: str = ""
    dotted: str = ""
    #: import alias -> canonical dotted path
    aliases: Dict[str, str] = field(default_factory=dict)
    #: lockref ("scope::sym") -> {"kind", "alias_of"}
    lock_defs: Dict[str, Dict[str, str]] = field(default_factory=dict)
    #: class name -> {"bases": [dotted], "attrs": {attr: dotted target}}
    #: where an attr binding is "C:<dotted class>" (constructor type)
    #: or "F:<dotted func>" (callable binding)
    classes: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    #: qual -> FuncSummary
    functions: Dict[str, FuncSummary] = field(default_factory=dict)
    #: handle_* suffixes defined here (the whole-tree RPC registry)
    handlers: List[str] = field(default_factory=list)
    #: derived-signal names defined by RecordingRule(name=...) here
    signals: List[str] = field(default_factory=list)

    def to_dict(self) -> Dict[str, Any]:
        return {
            "path": self.path, "sha": self.sha, "dotted": self.dotted,
            "aliases": self.aliases, "locks": self.lock_defs,
            "classes": self.classes,
            "functions": {q: f.to_dict()
                          for q, f in self.functions.items()},
            "handlers": self.handlers, "signals": self.signals,
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "ModuleSummary":
        return cls(
            path=d["path"], sha=d["sha"], dotted=d["dotted"],
            aliases=d["aliases"], lock_defs=d["locks"],
            classes=d["classes"],
            functions={q: FuncSummary.from_dict(f)
                       for q, f in d["functions"].items()},
            handlers=d["handlers"], signals=d["signals"],
        )


def module_dotted(path: str) -> str:
    """``ray_tpu/serve/kv_cache.py`` -> ``ray_tpu.serve.kv_cache``;
    package ``__init__.py`` maps to the package itself."""
    p = path[:-3] if path.endswith(".py") else path
    if p.endswith("/__init__"):
        p = p[: -len("/__init__")]
    return p.replace("/", ".")


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _collect_aliases(tree: ast.Module) -> Dict[str, str]:
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    aliases[alias.asname] = alias.name
                else:
                    top = alias.name.split(".")[0]
                    aliases[top] = top
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                aliases[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return aliases


def _resolve_dotted(aliases: Dict[str, str], d: Optional[str]
                    ) -> Optional[str]:
    if d is None:
        return None
    head, _, rest = d.partition(".")
    canon = aliases.get(head)
    if canon is not None:
        return f"{canon}.{rest}" if rest else canon
    return d


def _str_arg(call: ast.Call, index: int) -> Optional[str]:
    if len(call.args) > index and isinstance(call.args[index], ast.Constant) \
            and isinstance(call.args[index].value, str):
        return call.args[index].value
    return None


def _self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


# ---------------------------------------------------------------------------
# summarize: module-level structure
# ---------------------------------------------------------------------------

def _lock_ctor_kind(aliases: Dict[str, str], value: ast.AST
                    ) -> Optional[Tuple[str, Optional[ast.Call]]]:
    """(kind, ctor call) when ``value`` constructs a threading lock."""
    if not isinstance(value, ast.Call):
        return None
    d = _resolve_dotted(aliases, _dotted(value.func))
    if d is None or not d.startswith("threading."):
        return None
    kind = _LOCK_KINDS.get(d.split(".")[-1])
    return (kind, value) if kind else None


def _collect_lock_defs(tree: ast.Module, aliases: Dict[str, str]
                       ) -> Dict[str, Dict[str, str]]:
    """lockref -> def.  Scope is the enclosing class for ``self.X``
    assignments, ``""`` for module/function-level names.  A
    ``Condition(existing_lock)`` aliases the wrapped lock — both names
    guard the same mutex, so holding one IS holding the other."""
    defs: Dict[str, Dict[str, str]] = {}

    def handle(node: ast.AST, scope: str) -> None:
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            return
        value = node.value
        if value is None:
            return
        kc = _lock_ctor_kind(aliases, value)
        if kc is None:
            return
        kind, ctor = kc
        alias_of = ""
        if kind == "cond" and ctor is not None and ctor.args:
            wrapped = ctor.args[0]
            wsym = _self_attr(wrapped) or (
                wrapped.id if isinstance(wrapped, ast.Name) else None)
            if wsym:
                alias_of = f"{scope}::{wsym}"
        targets = node.targets if isinstance(node, ast.Assign) \
            else [node.target]
        for t in targets:
            sym = _self_attr(t) or (
                t.id if isinstance(t, ast.Name) else None)
            if sym:
                defs[f"{scope}::{sym}"] = {
                    "kind": kind, "alias_of": alias_of}

    # module-level names, then per-class self-attributes (the class
    # walk sees its methods' `self._lock = threading.Lock()` inits);
    # function-local locks are deliberately out of scope — they cannot
    # participate in a cross-function order cycle
    for node in tree.body:
        if isinstance(node, ast.ClassDef):
            for sub in ast.walk(node):
                handle(sub, node.name)
        else:
            handle(node, "")
    return defs


def _collect_attr_binds(cls_node: ast.ClassDef, aliases: Dict[str, str],
                        module_funcs: Set[str], dotted_mod: str
                        ) -> Dict[str, str]:
    """``self.<attr>`` bindings that type the receiver of later calls:
    ``self._kv = KVPageTable(...)`` binds ``_kv -> C:<dotted class>``;
    ``self._free = free or _default_free`` binds to the default
    callable (``F:<dotted func>``) — the common injectable-with-default
    pattern, where the default is what the tree actually runs."""
    binds: Dict[str, str] = {}

    def _callable_target(expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Call):
            d = _resolve_dotted(aliases, _dotted(expr.func))
            if d is None:
                return None
            if d.split(".")[-1][:1].isupper():
                return "C:" + (d if "." in d else f"{dotted_mod}.{d}")
            return None
        if isinstance(expr, ast.Name):
            if expr.id in module_funcs:
                return f"F:{dotted_mod}.{expr.id}"
            d = aliases.get(expr.id)
            if d is not None and "." in d:
                return f"F:{d}"
        return None

    for node in ast.walk(cls_node):
        if not isinstance(node, ast.Assign):
            continue
        attrs = [a for t in node.targets
                 if (a := _self_attr(t)) is not None]
        if not attrs:
            continue
        value = node.value
        candidates: List[ast.AST] = [value]
        if isinstance(value, ast.BoolOp):
            candidates = list(value.values)
        elif isinstance(value, ast.IfExp):
            candidates = [value.body, value.orelse]
        target = None
        for cand in candidates:
            target = _callable_target(cand)
            if target is not None:
                break
        if target is not None:
            for a in attrs:
                binds.setdefault(a, target)
    return binds


# ---------------------------------------------------------------------------
# summarize: per-function walk
# ---------------------------------------------------------------------------

def _rpc_site(call: ast.Call, aliases: Dict[str, str]
              ) -> Optional[Tuple[str, str]]:
    """(method, kind) for a literal-method RPC call site, or a blocking
    client entry point (kind='client', method=dotted name)."""
    method: Optional[str] = None
    kind = ""
    if isinstance(call.func, ast.Attribute):
        if call.func.attr == "call":
            method = _str_arg(call, 0) or _str_arg(call, 1)
            kind = "call"
        elif call.func.attr == "start_call":
            method = _str_arg(call, 0)
            kind = "start_call"
    d = _resolve_dotted(aliases, _dotted(call.func))
    if d is not None:
        tail = d.split(".")[-1]
        if tail == "call_with_retry":
            method = _str_arg(call, 1)
            kind = "retry"
        elif d in BLOCKING_CLIENT_CALLS:
            return d, "client"
    if method is None:
        return None
    return method, kind


def _idempotent_kw(call: ast.Call) -> str:
    for kw in call.keywords:
        if kw.arg == "idempotent" and isinstance(kw.value, ast.Constant):
            if kw.value.value is True:
                return "true"
            if kw.value.value is False:
                return "false"
    return ""


def _classify_call(call: ast.Call, aliases: Dict[str, str]
                   ) -> Optional[Tuple[str, str, str]]:
    """Module-local callee reference of one call site.

    Kinds: ``self`` (``self.m()``), ``attr`` (``self.<a>.m()``),
    ``local`` (``<var>.m()`` — resolved via local constructor types),
    ``dotted`` (alias-resolved dotted path, includes bare names).
    """
    func = call.func
    if isinstance(func, ast.Attribute):
        recv = func.value
        sa = _self_attr(func)
        if sa is not None:
            return "self", sa, ""
        inner = _self_attr(recv)
        if inner is not None:
            return "attr", inner, func.attr
        if isinstance(recv, ast.Name):
            # could be a local object or a module alias — record both
            # facets; the index tries local ctor types, then aliases
            return "local", recv.id, func.attr
        d = _resolve_dotted(aliases, _dotted(func))
        if d is not None:
            return "dotted", d, ""
        return None
    if isinstance(func, ast.Name):
        d = _resolve_dotted(aliases, _dotted(func))
        return "dotted", d or func.id, ""
    return None


class _FunctionWalker:
    """Sequential statement walk of one function body tracking the set
    of threading locks held at each call site (``with`` regions plus
    explicit acquire()/release() bracketing).  Nested function bodies
    are opaque — their statements run later, elsewhere."""

    def __init__(self, summary: FuncSummary, lock_defs: Dict[str, Dict],
                 aliases: Dict[str, str], cls: str):
        self.s = summary
        self.lock_defs = lock_defs
        self.aliases = aliases
        self.cls = cls
        self.held: List[str] = []

    # -- lock identity ----------------------------------------------------
    def _lockref(self, node: ast.AST) -> Optional[str]:
        """Resolve a with-item / acquire receiver to a lockref defined
        in this module (class scope first, then module scope)."""
        sym = _self_attr(node)
        if sym is not None:
            for scope in (self.cls, ""):
                ref = f"{scope}::{sym}"
                if ref in self.lock_defs:
                    return self._canon(ref)
            # self.X where X is a lock attr of ANOTHER class in this
            # module (mixin-style): match any class scope defining it
            for ref in self.lock_defs:
                if ref.endswith(f"::{sym}") and not ref.startswith("::"):
                    return self._canon(ref)
            return None
        if isinstance(node, ast.Name):
            ref = f"::{node.id}"
            return self._canon(ref) if ref in self.lock_defs else None
        return None

    def _canon(self, ref: str) -> str:
        seen = set()
        while ref in self.lock_defs and \
                self.lock_defs[ref].get("alias_of") and ref not in seen:
            seen.add(ref)
            nxt = self.lock_defs[ref]["alias_of"]
            if nxt not in self.lock_defs:
                break
            ref = nxt
        return ref

    # -- walk -------------------------------------------------------------
    def walk(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            pushed = 0
            for item in stmt.items:
                expr = item.context_expr
                recv = expr
                if isinstance(expr, ast.Call):
                    self._exprs(expr)
                    if isinstance(expr.func, ast.Attribute):
                        recv = expr.func.value
                ref = self._lockref(recv)
                if ref is not None:
                    self.s.acquires.append(
                        (ref, stmt.lineno, tuple(self.held)))
                    self.held.append(ref)
                    pushed += 1
            for sub in stmt.body:
                self._stmt(sub)
            for _ in range(pushed):
                self.held.pop()
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._exprs(stmt.test)
            for sub in stmt.body:
                self._stmt(sub)
            for sub in stmt.orelse:
                self._stmt(sub)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._exprs(stmt.iter)
            for sub in stmt.body:
                self._stmt(sub)
            for sub in stmt.orelse:
                self._stmt(sub)
            return
        if isinstance(stmt, ast.Try):
            for sub in stmt.body:
                self._stmt(sub)
            for handler in stmt.handlers:
                for sub in handler.body:
                    self._stmt(sub)
            for sub in stmt.orelse:
                self._stmt(sub)
            for sub in stmt.finalbody:
                self._stmt(sub)
            return
        # explicit acquire()/release() bracketing (sequential)
        if isinstance(stmt, ast.Expr) and isinstance(stmt.value, ast.Call):
            call = stmt.value
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in ("acquire", "release"):
                ref = self._lockref(call.func.value)
                if ref is not None:
                    if call.func.attr == "acquire":
                        self.s.acquires.append(
                            (ref, stmt.lineno, tuple(self.held)))
                        self.held.append(ref)
                    elif ref in self.held:
                        self.held.remove(ref)
                    return
        self._exprs(stmt)

    def _exprs(self, node: ast.AST) -> None:
        """Record every call in ``node`` with the current held set."""
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if not isinstance(sub, ast.Call):
                continue
            held = tuple(self.held)
            rpc = _rpc_site(sub, self.aliases)
            if rpc is not None:
                method, kind = rpc
                self.s.rpcs.append((method, kind, sub.lineno, held,
                                    _idempotent_kw(sub)))
            ref = _classify_call(sub, self.aliases)
            if ref is not None:
                kind, a, b = ref
                self.s.calls.append((kind, a, b, sub.lineno, held))
                lits = [f"{i}:{v.value}"
                        for i, v in enumerate(sub.args[:4])
                        if isinstance(v, ast.Constant)
                        and isinstance(v.value, str)]
                if lits:
                    self.s.call_lit_args.setdefault(
                        str(sub.lineno), []).extend(lits)


# -- retry/persist facts ----------------------------------------------------

class _StateFactsVisitor(ast.NodeVisitor):
    """Self-state writes, blind (replay-divergent) mutations, and the
    replay-guard shape, for the retry-safety rule."""

    def __init__(self, summary: FuncSummary):
        self.s = summary
        #: local name -> self attr it was derived from
        #: (``cur = self._metrics.get(key)`` — a later ``cur[...] +=``
        #: accumulates into that table through the local)
        self._derived: Dict[str, str] = {}

    def visit_FunctionDef(self, node):  # nested defs are opaque
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    @staticmethod
    def _rooted_attr(node: ast.AST) -> Optional[str]:
        while isinstance(node, ast.Subscript):
            node = node.value
        return _self_attr(node)

    def _derived_attr(self, node: ast.AST) -> Optional[str]:
        while isinstance(node, ast.Subscript):
            node = node.value
        if isinstance(node, ast.Name):
            return self._derived.get(node.id)
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            attr = self._rooted_attr(t)
            if attr is not None:
                self.s.writes_attrs.add(attr)
            if isinstance(t, ast.Name) and isinstance(node.value, ast.Call) \
                    and isinstance(node.value.func, ast.Attribute) \
                    and node.value.func.attr in ("get", "setdefault"):
                src = self._rooted_attr(node.value.func.value)
                if src is not None:
                    self._derived[t.id] = src
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self._rooted_attr(node.target)
        derived = self._derived_attr(node.target)
        if attr is not None:
            self.s.writes_attrs.add(attr)
        if isinstance(node.op, (ast.Add, ast.Sub)):
            which = attr or derived
            if which is not None:
                self.s.blind_ops.append((which, "aug", node.lineno))
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            attr = self._rooted_attr(t)
            if attr is not None:
                self.s.writes_attrs.add(attr)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        if isinstance(node.func, ast.Attribute):
            attr = self._rooted_attr(node.func.value) \
                or self._derived_attr(node.func.value)
            m = node.func.attr
            if attr is not None:
                if m in ("pop", "popitem", "update", "clear", "add",
                         "discard", "remove", "setdefault",
                         *_BLIND_METHODS):
                    self.s.writes_attrs.add(attr)
                if m in _BLIND_METHODS:
                    self.s.blind_ops.append((attr, m, node.lineno))
        self.generic_visit(node)

    def visit_If(self, node: ast.If) -> None:
        # replay-guard shape: `if <compare involving self state>:
        #     return/raise/continue` — the keyed early exit a
        # convergent handler uses to drop an already-applied delivery
        if not self.s.has_replay_guard:
            test_touches_self = any(
                _self_attr(sub) is not None
                or (isinstance(sub, ast.Name) and sub.id in self._derived)
                for sub in ast.walk(node.test))
            has_cmp = any(isinstance(sub, ast.Compare)
                          for sub in ast.walk(node.test))
            exits = any(isinstance(s, (ast.Return, ast.Raise,
                                       ast.Continue))
                        for s in node.body)
            if test_touches_self and has_cmp and exits:
                self.s.has_replay_guard = True
        self.generic_visit(node)


def _detect_retry_forward(fn: ast.AST, summary: FuncSummary,
                          aliases: Dict[str, str]) -> None:
    """A wrapper whose body forwards one of its params as
    ``call_with_retry``'s method arg (``def _gcs_call_retry(self,
    method, data)``) makes every literal-method call site of the
    wrapper a retrying call path."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if not isinstance(node, ast.Call):
            continue
        d = _resolve_dotted(aliases, _dotted(node.func))
        if d is None or d.split(".")[-1] != "call_with_retry":
            continue
        if len(node.args) > 1 and isinstance(node.args[1], ast.Name):
            name = node.args[1].id
            if name in summary.params:
                summary.retry_forward_param = summary.params.index(name)
                return


# ---------------------------------------------------------------------------
# summarize: path-sensitive resource lifecycle
# ---------------------------------------------------------------------------

class _Token:
    __slots__ = ("spec", "key", "line", "state", "protected", "alt")

    def __init__(self, spec: ResourceSpec, key: str, line: int,
                 alt: Optional[str] = None):
        self.spec = spec
        self.key = key          # var name or key-arg source text
        self.alt = alt          # bound result variable, when distinct
        self.line = line
        self.state = "held"     # held | released | escaped
        self.protected = False  # a finally/handler releases this spec

    def names(self) -> Set[str]:
        """Every name this token answers to: the key expression, its
        base, and the variable the acquire's result was bound to —
        ``lease = store.lease(oid)`` is released by key
        (``release(oid)``) but guarded/escaped by result
        (``if lease is None`` / ``out[k] = lease``)."""
        out = {self.key, self.key.split(".")[0].split("[")[0]}
        if self.alt:
            out.add(self.alt)
            out.add(self.alt.split(".")[0].split("[")[0])
        return out

    def key_matches(self, key: Optional[str]) -> bool:
        if key is None:
            return True
        if key in self.names():
            return True
        return key.endswith(self.key) or self.key.endswith(key)


def _expr_src(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse fallback
        return "<expr>"


def _base_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class _ResourceScanner:
    """Structured abstract interpretation of one function body for one
    set of resource specs.  Tracks acquisitions to their release /
    ownership escape; reports a leak when a path exits (return, fall
    off the end, explicit raise) with a live token, and — for
    strict-exception specs — when a raising statement sits in the held
    region with no protecting finally/handler."""

    def __init__(self, summary: FuncSummary, aliases: Dict[str, str],
                 specs: Sequence[ResourceSpec]):
        self.s = summary
        self.aliases = aliases
        self.specs = specs
        self.tokens: List[_Token] = []
        #: specs released in an enclosing finally/except (stack depth)
        self._protect: List[Set[str]] = []

    # -- site matching ----------------------------------------------------
    def _acquire_of(self, call: ast.Call) -> Optional[ResourceSpec]:
        if isinstance(call.func, ast.Attribute):
            m = call.func.attr
            recv_sym = _self_attr(call.func.value) or (
                call.func.value.id
                if isinstance(call.func.value, ast.Name) else
                call.func.value.attr
                if isinstance(call.func.value, ast.Attribute) else None)
            for spec in self.specs:
                if m in spec.acquire_methods and (
                        not spec.receiver_hints
                        or recv_sym in spec.receiver_hints):
                    return spec
        d = _resolve_dotted(self.aliases, _dotted(call.func))
        if d is not None:
            tail = d.split(".")[-1]
            for spec in self.specs:
                if d in spec.acquire_funcs or tail in spec.acquire_funcs:
                    return spec
        return None

    def _match_release(self, call: ast.Call) -> Optional[Tuple[
            ResourceSpec, Optional[str], bool]]:
        """(spec, key-or-None, release_all) when ``call`` is a release
        site of one of our specs."""
        if isinstance(call.func, ast.Attribute):
            m = call.func.attr
            recv = call.func.value
            for spec in self.specs:
                if m in spec.release_methods:
                    key = _expr_src(call.args[0]) if call.args else None
                    return spec, key, False
                if m in spec.release_value_methods:
                    return spec, _expr_src(recv), False
        d = _resolve_dotted(self.aliases, _dotted(call.func))
        if d is not None:
            tail = d.split(".")[-1]
            for spec in self.specs:
                if d in spec.release_funcs or tail in spec.release_funcs:
                    key = _expr_src(call.args[0]) if call.args else None
                    return spec, key, False
                if d in spec.release_all_funcs \
                        or tail in spec.release_all_funcs:
                    return spec, None, True
        return None

    def _is_borrow(self, spec: ResourceSpec, call: ast.Call) -> bool:
        d = _resolve_dotted(self.aliases, _dotted(call.func))
        if d is None:
            return False
        tail = d.split(".")[-1]
        return d in spec.borrows or tail in spec.borrows

    # -- token ops --------------------------------------------------------
    def _live(self) -> List[_Token]:
        return [t for t in self.tokens if t.state == "held"]

    def _release(self, spec: ResourceSpec, key: Optional[str],
                 release_all: bool) -> None:
        for t in self.tokens:
            if t.spec.name != spec.name or t.state != "held":
                continue
            if release_all or t.key_matches(key):
                t.state = "released"

    def _escape_names(self, node: ast.AST) -> None:
        """Any live token whose name (key, key base, or bound result)
        flows into ``node`` — stored, returned, yielded, or passed to
        a non-borrow call — escapes ownership."""
        names: Set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                names.add(sub.id)
        if not names:
            return
        for t in self._live():
            if t.names() & names:
                t.state = "escaped"

    def _call_args_escape(self, call: ast.Call) -> None:
        rel = self._match_release(call)
        for t in self._live():
            if rel is not None and rel[0].name == t.spec.name:
                continue
            if self._is_borrow(t.spec, call):
                continue
            tnames = t.names()
            for arg in list(call.args) + [kw.value for kw in call.keywords]:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name) and sub.id in tnames:
                        t.state = "escaped"
                        break

    def _handle_calls(self, node: ast.AST) -> None:
        """Releases and argument-escapes for every call in ``node``."""
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, ast.Call):
                rel = self._match_release(sub)
                if rel is not None:
                    self._release(*rel)
                self._call_args_escape(sub)

    #: callee tails that do not raise in practice — container access,
    #: id formatting, clock reads, logging.  Without this, every
    #: ``conn.context.setdefault(...)`` between an acquire and its
    #: escape is an "exception edge" and the strict specs drown in
    #: noise.  An await or any other call still counts as raising.
    _SAFE_CALLEE_TAILS = frozenset({
        "get", "setdefault", "pop", "add", "discard", "append",
        "items", "keys", "values", "copy", "update", "len",
        "hex", "binary", "monotonic", "time", "isinstance",
        "debug", "info", "warning", "error", "exception",
        # container constructors (empty or copying a known container)
        "set", "dict", "list", "tuple", "frozenset",
    })

    @classmethod
    def _can_raise(cls, stmt: ast.stmt) -> bool:
        for sub in ast.walk(stmt):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                continue
            if isinstance(sub, (ast.Await, ast.Raise)):
                return True
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func)
                tail = d.split(".")[-1] if d else ""
                if tail not in cls._SAFE_CALLEE_TAILS:
                    return True
        return False

    def _leak(self, t: _Token, line: int, kind: str) -> None:
        t.state = "escaped"  # report once per acquisition
        self.s.res_leaks.append((t.spec.name, t.key, t.line, line, kind))

    # -- statement walk ---------------------------------------------------
    def walk(self, body: List[ast.stmt], end_line: int) -> None:
        self._stmts(body)
        for t in self._live():
            self._leak(t, end_line, "exit")

    def _stmts(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            self._stmt(stmt)

    def _acquire_in(self, node: ast.AST
                    ) -> Optional[Tuple[ResourceSpec, ast.Call]]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                spec = self._acquire_of(sub)
                if spec is not None:
                    return spec, sub
        return None

    def _protected(self, spec: ResourceSpec) -> bool:
        return any(spec.name in s for s in self._protect)

    def _stmt(self, stmt: ast.stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return

        # strict-exception check BEFORE interpreting the statement: a
        # raising statement while a token is held and unprotected is an
        # exception-edge leak (the acquire statement itself is exempt)
        if self._can_raise(stmt) and not isinstance(stmt, ast.Raise):
            for t in self._live():
                if t.spec.strict_exceptions and not t.protected \
                        and not self._protected(t.spec) \
                        and stmt.lineno > t.line:
                    # the statement that releases/escapes this very
                    # token is not an exception hazard for it — probe
                    # on a copy of the interpretation
                    if self._stmt_settles(stmt, t):
                        continue
                    self._leak(t, stmt.lineno, "exception")

        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            acq = self._acquire_in(stmt) if value is not None else None
            self._handle_calls(stmt)
            if acq is not None:
                spec, call = acq
                targets = stmt.targets if isinstance(stmt, ast.Assign) \
                    else [stmt.target]
                key = None
                alt = None
                if targets and isinstance(targets[0],
                                          (ast.Name, ast.Attribute)):
                    alt = _expr_src(targets[0])
                if spec.key_arg is not None \
                        and len(call.args) > spec.key_arg:
                    key = _expr_src(call.args[spec.key_arg])
                elif alt is not None:
                    key, alt = alt, None
                if key is not None:
                    tok = _Token(spec, key, stmt.lineno, alt=alt)
                    tok.protected = self._protected(spec)
                    self.tokens.append(tok)
                    if spec.key_arg is None and not isinstance(
                            targets[0], ast.Name):
                        tok.state = "escaped"  # stored straight away
            else:
                # a live token stored into a container/attribute is an
                # ownership escape (released elsewhere, by the owner)
                if isinstance(stmt, ast.Assign):
                    for t_node in stmt.targets:
                        if isinstance(t_node, (ast.Attribute,
                                               ast.Subscript)):
                            if stmt.value is not None:
                                self._escape_names(stmt.value)
            return

        if isinstance(stmt, ast.Expr):
            acq = self._acquire_in(stmt)
            self._handle_calls(stmt)
            if acq is not None:
                spec, call = acq
                if spec.key_arg is not None \
                        and len(call.args) > spec.key_arg:
                    tok = _Token(spec, _expr_src(call.args[spec.key_arg]),
                                 stmt.lineno)
                    tok.protected = self._protected(spec)
                    self.tokens.append(tok)
                elif not spec.checked:
                    # unassigned value-token acquire: nothing can ever
                    # release it — immediate leak
                    self.s.res_leaks.append(
                        (spec.name, "<unassigned>", stmt.lineno,
                         stmt.lineno, "unassigned"))
            return

        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._handle_calls(stmt.value)
                self._escape_names(stmt.value)
            for t in self._live():
                if not t.protected and not self._protected(t.spec):
                    self._leak(t, stmt.lineno, "exit")
            return

        if isinstance(stmt, ast.Raise):
            if stmt.exc is not None:
                self._handle_calls(stmt.exc)
            for t in self._live():
                if t.spec.strict_exceptions and not t.protected \
                        and not self._protected(t.spec):
                    self._leak(t, stmt.lineno, "exception")
            return

        if isinstance(stmt, ast.If):
            self._branch_if(stmt)
            return

        if isinstance(stmt, (ast.While, ast.For, ast.AsyncFor)):
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._handle_calls(stmt.iter)
            else:
                self._handle_calls(stmt.test)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            return

        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                if isinstance(item.context_expr, ast.Call):
                    spec = self._acquire_of(item.context_expr)
                    if spec is not None:
                        continue  # context manager releases it
                self._handle_calls(item.context_expr)
            self._stmts(stmt.body)
            return

        if isinstance(stmt, ast.Try):
            # which specs does a finally/handler release?  tokens held
            # through the body are protected for those specs
            protected: Set[str] = set()
            for blk in [stmt.finalbody] + [h.body for h in stmt.handlers]:
                for sub_stmt in blk:
                    for sub in ast.walk(sub_stmt):
                        if isinstance(sub, ast.Call):
                            rel = self._match_release(sub)
                            if rel is not None:
                                protected.add(rel[0].name)
            self._protect.append(protected)
            before = set(id(t) for t in self.tokens)
            self._stmts(stmt.body)
            self._stmts(stmt.orelse)
            self._protect.pop()
            # tokens acquired inside the try body are suspended while
            # walking the except handlers: the dominant pattern is
            # ``try: fd = os.open(...) except OSError: return None`` —
            # in that path the acquire itself failed, nothing is held
            acquired_in_body = [t for t in self.tokens
                                if id(t) not in before]
            saved = [(t, t.state) for t in acquired_in_body]
            for t in acquired_in_body:
                if t.state == "held":
                    t.state = "released"
            for handler in stmt.handlers:
                self._stmts(handler.body)
            for t, st in saved:
                if t.state == "released":
                    t.state = st
            self._stmts(stmt.finalbody)
            return

        # default: releases/escapes inside, no control flow
        self._handle_calls(stmt)

    def _stmt_settles(self, stmt: ast.stmt, t: _Token) -> bool:
        """True when ``stmt`` itself releases or escapes ``t`` — then
        it is not an exception hazard *for that token* (if it raises,
        the release raced the failure; treating that as a leak would
        flag every `release()` call that can itself fail)."""
        tnames = t.names()
        for sub in ast.walk(stmt):
            if not isinstance(sub, ast.Call):
                continue
            rel = self._match_release(sub)
            if rel is not None and rel[0].name == t.spec.name \
                    and (rel[2] or t.key_matches(rel[1])):
                return True
            if self._is_borrow(t.spec, sub):
                continue
            for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                for inner in ast.walk(arg):
                    if isinstance(inner, ast.Name) and inner.id in tnames:
                        return True
        names = {n.id for n in ast.walk(stmt) if isinstance(n, ast.Name)}
        if isinstance(stmt, (ast.Return, ast.Assign)) and tnames & names:
            return True
        return False

    def _branch_if(self, stmt: ast.If) -> None:
        """If handling with result-check refinement: for a ``checked``
        acquire, ``if tok is None: ...`` / ``if not ok: ...`` drops the
        token in the failure branch (nothing was acquired there)."""
        self._handle_calls(stmt.test)
        acq = self._acquire_in(stmt.test)
        if acq is not None:
            spec, call = acq
            if spec.checked:
                key = None
                if spec.key_arg is not None \
                        and len(call.args) > spec.key_arg:
                    key = _expr_src(call.args[spec.key_arg])
                if key is not None:
                    positive_body = not isinstance(stmt.test,
                                                   ast.UnaryOp)
                    tok = _Token(spec, key, stmt.lineno)
                    tok.protected = self._protected(spec)
                    self.tokens.append(tok)
                    if positive_body:
                        # held only inside the body
                        self._stmts(stmt.body)
                        tok.state = "escaped" if tok.state == "held" \
                            else tok.state
                        saved = tok.state
                        self._stmts(stmt.orelse)
                        tok.state = saved
                    else:
                        # `if not acquire(): break/return` — held on
                        # the fallthrough
                        self._stmts(stmt.body)
                        self._stmts(stmt.orelse)
                    return
        failure, success = self._none_guard(stmt.test)
        if failure is not None:
            # the token's value is None/falsy in the body — the acquire
            # failed on that path, so nothing is held while walking it
            for t in self._live():
                if failure in t.names():
                    t.state = "released"
                    self._stmts(stmt.body)
                    if t.state == "released":
                        t.state = "held"
                    self._stmts(stmt.orelse)
                    return
        if success is not None:
            for t in self._live():
                if success in t.names():
                    self._stmts(stmt.body)
                    body_state = t.state
                    t.state = "released"  # not held in the else branch
                    self._stmts(stmt.orelse)
                    if t.state == "released":
                        t.state = body_state
                    return
        self._stmts(stmt.body)
        self._stmts(stmt.orelse)

    @staticmethod
    def _none_guard(test: ast.AST) -> Tuple[Optional[str], Optional[str]]:
        """(failure-name, success-name): ``x is None`` / ``not x`` put
        the token's FAILURE branch in the body; ``x is not None`` / a
        bare name put the SUCCESS branch there."""
        if isinstance(test, ast.Compare) and len(test.ops) == 1 \
                and isinstance(test.comparators[0], ast.Constant) \
                and test.comparators[0].value is None:
            name = _expr_src(test.left)
            if isinstance(test.ops[0], ast.Is):
                return name, None
            if isinstance(test.ops[0], ast.IsNot):
                return None, name
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            inner = test.operand
            if isinstance(inner, (ast.Name, ast.Attribute)):
                return _expr_src(inner), None
        if isinstance(test, (ast.Name, ast.Attribute)):
            return None, _expr_src(test)
        return None, None


# ---------------------------------------------------------------------------
# summarize_module
# ---------------------------------------------------------------------------

def summarize_module(path: str, source: str,
                     tree: Optional[ast.Module] = None,
                     specs: Sequence[ResourceSpec] = RESOURCE_SPECS
                     ) -> ModuleSummary:
    if tree is None:
        tree = ast.parse(source, filename=path)
    aliases = _collect_aliases(tree)
    dotted_mod = module_dotted(path)
    ms = ModuleSummary(
        path=path,
        sha=hashlib.sha256(source.encode()).hexdigest(),
        dotted=dotted_mod, aliases=aliases,
        lock_defs=_collect_lock_defs(tree, aliases))

    module_funcs = {n.name for n in tree.body
                    if isinstance(n, (ast.FunctionDef,
                                      ast.AsyncFunctionDef))}

    def _summarize_fn(node, cls_name: str) -> None:
        qual = f"{cls_name}.{node.name}" if cls_name else node.name
        fs = FuncSummary(
            qual=qual, cls=cls_name, name=node.name, line=node.lineno,
            is_async=isinstance(node, ast.AsyncFunctionDef),
            params=tuple(a.arg for a in node.args.args))
        _FunctionWalker(fs, ms.lock_defs, aliases, cls_name).walk(node.body)
        sf = _StateFactsVisitor(fs)
        for stmt in node.body:
            sf.visit(stmt)
        _detect_retry_forward(node, fs, aliases)
        end = max((getattr(n, "lineno", node.lineno)
                   for n in ast.walk(node)), default=node.lineno)
        _ResourceScanner(fs, aliases, specs).walk(node.body, end)
        ms.functions[qual] = fs
        if node.name.startswith("handle_"):
            ms.handlers.append(node.name[len("handle_"):])

    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _summarize_fn(node, "")
        elif isinstance(node, ast.ClassDef):
            bases = []
            for b in node.bases:
                d = _resolve_dotted(aliases, _dotted(b))
                if d is not None:
                    bases.append(d if "." in d else f"{dotted_mod}.{d}")
            ms.classes[node.name] = {
                "bases": bases,
                "attrs": _collect_attr_binds(node, aliases,
                                             module_funcs, dotted_mod),
            }
            for sub in node.body:
                if isinstance(sub, (ast.FunctionDef,
                                    ast.AsyncFunctionDef)):
                    _summarize_fn(sub, node.name)

    # derived-signal definitions (metric-drift consults the whole tree)
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            if d is not None and d.split(".")[-1] == "RecordingRule":
                for kw in node.keywords:
                    if kw.arg == "name" \
                            and isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, str):
                        ms.signals.append(kw.value.value)
    return ms


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

def default_cache_path(root: str) -> str:
    return os.path.join(root, "build", "rtpu-check-summaries.pkl")


class SummaryCache:
    """Content-hash-keyed persistence of module summaries.  The cache
    file lives under ``build/`` (gitignored, wiped by ``make clean``);
    a version or spec-fingerprint mismatch drops it wholesale.  Pickle,
    not JSON: the doc holds every per-function summary in the tree
    (~hundreds of thousands of nodes) and is rewritten whole on any
    edit, so codec speed is what keeps ``--changed-only`` sub-second —
    same local-build-artifact trust model as ``.pyc``."""

    def __init__(self, path: Optional[str],
                 specs: Sequence[ResourceSpec] = RESOURCE_SPECS):
        self.path = path
        self._fp = _spec_fingerprint(specs)
        self._entries: Dict[str, Dict[str, Any]] = {}
        self._dirty = False
        self.hits = 0
        self.misses = 0
        if path is not None and os.path.exists(path):
            try:
                with open(path, "rb") as f:
                    data = pickle.load(f)
                if data.get("version") == CACHE_VERSION \
                        and data.get("specs") == self._fp:
                    self._entries = data.get("modules", {})
            except (OSError, ValueError, EOFError, AttributeError,
                    ImportError, pickle.PickleError):
                self._entries = {}

    def get(self, path: str, sha: str) -> Optional[ModuleSummary]:
        ent = self._entries.get(path)
        if ent is not None and ent.get("sha") == sha:
            self.hits += 1
            try:
                return ModuleSummary.from_dict(ent["summary"])
            except (KeyError, TypeError):  # pragma: no cover - corrupt
                pass
        self.misses += 1
        return None

    def put(self, summary: ModuleSummary) -> None:
        self._entries[summary.path] = {
            "sha": summary.sha, "summary": summary.to_dict()}
        self._dirty = True

    def save(self) -> None:
        # a fully-warm run re-summarized nothing: skip the (large)
        # re-serialization entirely
        if self.path is None or not self._dirty:
            return
        try:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "wb") as f:
                pickle.dump({"version": CACHE_VERSION, "specs": self._fp,
                             "modules": self._entries}, f,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self.path)
        except OSError:  # pragma: no cover - cache is best-effort
            pass


# ---------------------------------------------------------------------------
# project index
# ---------------------------------------------------------------------------

class ProjectIndex:
    """The resolved whole-program view: module summaries keyed by path,
    a global function table, class registry, call resolution, and the
    transitive fixed points the rules consume."""

    def __init__(self) -> None:
        self.modules: Dict[str, ModuleSummary] = {}
        self.by_dotted: Dict[str, str] = {}
        self.classes: Dict[str, Tuple[str, str]] = {}
        self.functions: Dict[str, FuncSummary] = {}   # fid -> summary
        self._fn_module: Dict[str, str] = {}          # fid -> path
        self._resolve_memo: Dict[Tuple, Optional[str]] = {}
        self._trans_locks: Optional[Dict[str, Set[str]]] = None
        self._trans_rpc: Optional[Dict[str, Set[str]]] = None
        self._callees_memo: Dict[str, List[Tuple[str, int]]] = {}

    # -- construction -----------------------------------------------------
    @classmethod
    def build(cls, summaries: Iterable[ModuleSummary]) -> "ProjectIndex":
        idx = cls()
        for ms in summaries:
            idx.add(ms)
        return idx

    def add(self, ms: ModuleSummary) -> None:
        self.modules[ms.path] = ms
        self.by_dotted[ms.dotted] = ms.path
        for cname in ms.classes:
            self.classes[f"{ms.dotted}.{cname}"] = (ms.path, cname)
        for qual, fs in ms.functions.items():
            fid = f"{ms.path}::{qual}"
            self.functions[fid] = fs
            self._fn_module[fid] = ms.path

    @classmethod
    def from_tree(cls, root: str,
                  cache: Optional[SummaryCache] = None,
                  extra_sources: Optional[Dict[str, str]] = None,
                  specs: Sequence[ResourceSpec] = RESOURCE_SPECS
                  ) -> "ProjectIndex":
        """Index every ``ray_tpu/`` module under ``root``, consulting
        ``cache`` by content hash.  ``extra_sources`` (path -> source)
        overrides/augments the on-disk tree (used by tests and by
        scans whose contexts were already read)."""
        summaries: List[ModuleSummary] = []
        sources: Dict[str, str] = dict(extra_sources or {})
        pkg = os.path.join(root, "ray_tpu")
        if os.path.isdir(pkg):
            for dirpath, dirnames, filenames in os.walk(pkg):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for fn in sorted(filenames):
                    if not fn.endswith(".py"):
                        continue
                    rel = os.path.relpath(
                        os.path.join(dirpath, fn), root
                    ).replace(os.sep, "/")
                    if rel in sources:
                        continue
                    try:
                        with open(os.path.join(dirpath, fn),
                                  encoding="utf-8") as f:
                            sources[rel] = f.read()
                    except OSError:
                        continue
        for rel in sorted(sources):
            source = sources[rel]
            sha = hashlib.sha256(source.encode()).hexdigest()
            ms = cache.get(rel, sha) if cache is not None else None
            if ms is None:
                try:
                    ms = summarize_module(rel, source, specs=specs)
                except SyntaxError:
                    continue
                if cache is not None:
                    cache.put(ms)
            summaries.append(ms)
        return cls.build(summaries)

    # -- registries -------------------------------------------------------
    def all_handlers(self) -> Dict[str, List[Tuple[str, str, int]]]:
        """method -> [(path, qual, line)] over the whole tree."""
        out: Dict[str, List[Tuple[str, str, int]]] = {}
        for path, ms in self.modules.items():
            for qual, fs in ms.functions.items():
                if fs.name.startswith("handle_"):
                    out.setdefault(fs.name[len("handle_"):], []).append(
                        (path, qual, fs.line))
        return out

    def all_signals(self) -> Set[str]:
        return {s for ms in self.modules.values() for s in ms.signals}

    def dependents(self, paths: Iterable[str]) -> Set[str]:
        """Modules that import (directly) any of ``paths`` — the
        ``--changed-only`` blast radius."""
        targets = {self.modules[p].dotted for p in paths
                   if p in self.modules}
        out: Set[str] = set()
        for path, ms in self.modules.items():
            for dotted in ms.aliases.values():
                d = dotted
                while d:
                    if d in targets:
                        out.add(path)
                        break
                    d = d.rpartition(".")[0]
                else:
                    continue
                break
        return out

    # -- call resolution --------------------------------------------------
    def _class_function(self, dotted_cls: str, meth: str,
                        depth: int = 0) -> Optional[str]:
        ent = self.classes.get(dotted_cls)
        if ent is None or depth > 6:
            return None
        path, cname = ent
        ms = self.modules[path]
        qual = f"{cname}.{meth}"
        if qual in ms.functions:
            return f"{path}::{qual}"
        for base in ms.classes[cname]["bases"]:
            hit = self._class_function(base, meth, depth + 1)
            if hit is not None:
                return hit
        return None

    def _module_function(self, dotted: str) -> Optional[str]:
        """``pkg.mod.func`` (or ``pkg.mod.Class.meth``) -> fid."""
        mod, _, name = dotted.rpartition(".")
        if not mod:
            return None
        path = self.by_dotted.get(mod)
        if path is not None:
            ms = self.modules[path]
            if name in ms.functions:
                return f"{path}::{name}"
            if name in ms.classes:  # constructor: Class() -> __init__
                return self._class_function(dotted, "__init__")
        # Class.meth spelled dotted (mod.Class.meth)
        mod2, _, cls_name = mod.rpartition(".")
        if mod2 and self.by_dotted.get(mod2) is not None \
                and cls_name[:1].isupper():
            return self._class_function(f"{mod2}.{cls_name}", name)
        return None

    def resolve_call(self, path: str, fs: FuncSummary,
                     kind: str, a: str, b: str) -> Optional[str]:
        memo_key = (path, fs.cls, kind, a, b)
        if memo_key in self._resolve_memo:
            return self._resolve_memo[memo_key]
        out = self._resolve_call(path, fs, kind, a, b)
        self._resolve_memo[memo_key] = out
        return out

    def _resolve_call(self, path: str, fs: FuncSummary,
                      kind: str, a: str, b: str) -> Optional[str]:
        ms = self.modules.get(path)
        if ms is None:
            return None
        if kind == "self":
            if fs.cls:
                hit = self._class_function(f"{ms.dotted}.{fs.cls}", a)
                if hit is not None:
                    return hit
            # self.<attr>() where attr is a bound callable
            if fs.cls and fs.cls in ms.classes:
                bind = ms.classes[fs.cls]["attrs"].get(a)
                if bind is not None and bind.startswith("F:"):
                    return self._module_function(bind[2:])
            return None
        if kind == "attr":
            if fs.cls and fs.cls in ms.classes:
                bind = ms.classes[fs.cls]["attrs"].get(a)
                if bind is not None:
                    if bind.startswith("C:"):
                        return self._class_function(bind[2:], b)
                    if bind.startswith("F:") and not b:
                        return self._module_function(bind[2:])
            return None
        if kind == "local":
            # <name>.<meth> — try the name as a module alias first
            d = ms.aliases.get(a)
            if d is not None:
                return self._module_function(f"{d}.{b}")
            return None
        if kind == "dotted":
            d = a
            head, _, rest = d.partition(".")
            canon = ms.aliases.get(head)
            if canon is not None:
                d = f"{canon}.{rest}" if rest else canon
            elif "." not in d:
                if d in ms.functions:
                    return f"{path}::{d}"
                if d in ms.classes:
                    return self._class_function(f"{ms.dotted}.{d}",
                                                "__init__")
                return None
            return self._module_function(d)
        return None

    def callees(self, fid: str) -> List[Tuple[str, int]]:
        """Resolved (callee fid, call line) list of one function."""
        cached = self._callees_memo.get(fid)
        if cached is not None:
            return cached
        fs = self.functions[fid]
        path = self._fn_module[fid]
        out: List[Tuple[str, int]] = []
        for kind, a, b, line, _held in fs.calls:
            tgt = self.resolve_call(path, fs, kind, a, b)
            if tgt is not None and tgt != fid:
                out.append((tgt, line))
        self._callees_memo[fid] = out
        return out

    # -- transitive fixed points ------------------------------------------
    def lock_id(self, path: str, lockref: str) -> str:
        scope, _, sym = lockref.partition("::")
        return f"{path}::{scope}.{sym}" if scope else f"{path}::{sym}"

    def lock_kind(self, lock_id: str) -> str:
        path, _, rest = lock_id.partition("::")
        scope, _, sym = rest.rpartition(".")
        ms = self.modules.get(path)
        if ms is None:
            return "lock"
        d = ms.lock_defs.get(f"{scope}::{sym}")
        return d["kind"] if d else "lock"

    def _fixed_point(self, direct: Dict[str, Set[str]]
                     ) -> Dict[str, Set[str]]:
        out = {fid: set(v) for fid, v in direct.items()}
        edges: Dict[str, List[str]] = {
            fid: [c for c, _ in self.callees(fid)]
            for fid in self.functions}
        changed = True
        while changed:
            changed = False
            for fid, callees in edges.items():
                cur = out.setdefault(fid, set())
                before = len(cur)
                for c in callees:
                    cur |= out.get(c, set())
                if len(cur) != before:
                    changed = True
        return out

    def transitive_locks(self) -> Dict[str, Set[str]]:
        """fid -> every lock id it may acquire, directly or through
        resolved callees."""
        if self._trans_locks is None:
            direct = {
                fid: {self.lock_id(self._fn_module[fid], ref)
                      for ref, _ln, _held in fs.acquires}
                for fid, fs in self.functions.items()}
            self._trans_locks = self._fixed_point(direct)
        return self._trans_locks

    def transitive_rpcs(self) -> Dict[str, Set[str]]:
        """fid -> blocking RPC markers reachable from it.  Only SYNC
        reachability counts: an async callee's awaited RPC parks the
        caller's coroutine (the per-file await-under-lock rule owns
        that); what this tracks is a *thread* blocking inside a sync
        call chain."""
        if self._trans_rpc is None:
            direct: Dict[str, Set[str]] = {}
            for fid, fs in self.functions.items():
                marks = {f"{m}" for m, kind, _ln, _held, _idem in fs.rpcs
                         if kind == "client"}
                direct[fid] = marks
            # restrict propagation to sync callees: an awaited coroutine
            # does not block the thread that owns the lock
            out = {fid: set(v) for fid, v in direct.items()}
            edges = {
                fid: [c for c, _ in self.callees(fid)
                      if not self.functions[c].is_async]
                for fid in self.functions}
            changed = True
            while changed:
                changed = False
                for fid, callees in edges.items():
                    cur = out.setdefault(fid, set())
                    before = len(cur)
                    for c in callees:
                        cur |= out.get(c, set())
                    if len(cur) != before:
                        changed = True
            self._trans_rpc = out
        return self._trans_rpc

    # -- witness chains ---------------------------------------------------
    def find_chain(self, start: str,
                   want: Callable[[str], Optional[int]],
                   sync_only: bool = False
                   ) -> Optional[List[Tuple[str, int]]]:
        """BFS from ``start`` to the nearest function where ``want``
        returns a line number; the chain is [(fid, line-at-which-the-
        next-hop-happens), ..., (final fid, target line)]."""
        hit = want(start)
        if hit is not None:
            return [(start, hit)]
        parents: Dict[str, Tuple[str, int]] = {}
        queue = [start]
        seen = {start}
        while queue:
            cur = queue.pop(0)
            for callee, line in self.callees(cur):
                if callee in seen:
                    continue
                if sync_only and self.functions[callee].is_async:
                    continue
                seen.add(callee)
                parents[callee] = (cur, line)
                hit = want(callee)
                if hit is not None:
                    chain: List[Tuple[str, int]] = [(callee, hit)]
                    node = callee
                    while node in parents:
                        parent, pline = parents[node]
                        chain.insert(0, (parent, pline))
                        node = parent
                    return chain
                queue.append(callee)
        return None

    def render_fid(self, fid: str) -> str:
        path, _, qual = fid.partition("::")
        return f"{path}:{qual}"

    def render_chain(self, chain: List[Tuple[str, int]]) -> str:
        return " -> ".join(f"{self.render_fid(fid)}:{line}"
                           for fid, line in chain)


def index_for(contexts: Sequence[Any], cfg: Any,
              cache: Optional[SummaryCache] = None) -> ProjectIndex:
    """The project index for one run: scanned contexts (any objects
    with ``.path``/``.source``) override the on-disk tree under
    ``cfg.root``.  Memoized on the config object so the three
    interprocedural rules — and the registry consumers in project.py —
    build it exactly once per run (and per test fixture)."""
    idx = getattr(cfg, "ipa_index", None)
    if idx is not None:
        return idx
    idx = ProjectIndex.from_tree(
        cfg.root, cache=cache,
        extra_sources={c.path: c.source for c in contexts})
    cfg.ipa_index = idx
    return idx
