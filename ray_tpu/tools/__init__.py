"""Developer tooling that ships with the runtime (static analysis, etc.)."""
