"""Cluster-level job lifecycle.

Parity: reference ``dashboard/modules/job/job_manager.py``
(``JobManager``:431, ``JobSupervisor``:133) — an entrypoint shell
command runs as a subprocess of a detached supervisor actor; status and
logs live in the GCS KV, so any client (REST, SDK, CLI) can query them
without touching the supervisor.
"""

from __future__ import annotations

import json
import os
import subprocess
import time
import uuid
from typing import Any, Dict, List, Optional

import ray_tpu

JOB_KV_NS = "job"

# terminal states (reference JobStatus)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"
TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def _kv():
    from ray_tpu.core import worker as worker_mod
    return worker_mod.global_worker()


def _put_info(submission_id: str, info: Dict[str, Any]) -> None:
    _kv().kv_put(f"info:{submission_id}", json.dumps(info).encode(),
                 namespace=JOB_KV_NS)


def _get_info(submission_id: str) -> Optional[Dict[str, Any]]:
    blob = _kv().kv_get(f"info:{submission_id}", namespace=JOB_KV_NS)
    return json.loads(blob) if blob else None


class JobSupervisor:
    """Detached actor owning one job's subprocess (reference :133)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 metadata: Dict[str, str], env_vars: Dict[str, str],
                 log_path: str):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self.log_path = log_path
        self.proc: Optional[subprocess.Popen] = None
        self._stopped = False
        env = dict(os.environ)
        env.update(env_vars or {})
        # the job driver must find this framework regardless of its cwd
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.abspath(ray_tpu.__file__)))
        env["PYTHONPATH"] = pkg_root + os.pathsep + env.get("PYTHONPATH", "")
        # the job driver joins this cluster, not a new one
        info = ray_tpu.connection_info()
        gcs = info.get("gcs_address")
        if gcs:
            env["RAY_TPU_ADDRESS"] = f"{gcs[0]}:{gcs[1]}"
        os.makedirs(os.path.dirname(log_path), exist_ok=True)
        self._log_f = open(log_path, "ab", buffering=0)
        self.proc = subprocess.Popen(
            entrypoint, shell=True, env=env,
            stdout=self._log_f, stderr=subprocess.STDOUT,
            start_new_session=True)
        info_rec = _get_info(submission_id) or {}
        info_rec.update(status=RUNNING, start_time=time.time())
        _put_info(submission_id, info_rec)

    def wait(self) -> str:
        """Block until the entrypoint exits; record the terminal state."""
        code = self.proc.wait()
        info = _get_info(self.submission_id) or {}
        if self._stopped:
            status = STOPPED
        else:
            status = SUCCEEDED if code == 0 else FAILED
        info.update(status=status, end_time=time.time(), exit_code=code)
        _put_info(self.submission_id, info)
        return status

    def stop(self) -> bool:
        self._stopped = True
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
        return True

    def ping(self) -> bool:
        return True


class JobManager:
    """Driver-side job orchestration (reference ``JobManager``:431)."""

    def __init__(self, log_dir: Optional[str] = None):
        self.log_dir = log_dir or os.path.join(
            os.environ.get("TMPDIR", "/tmp"), "ray_tpu_jobs")

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   runtime_env: Optional[Dict[str, Any]] = None
                   ) -> str:
        submission_id = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        if _get_info(submission_id) is not None:
            raise ValueError(f"job {submission_id!r} already exists")
        log_path = os.path.join(self.log_dir, f"{submission_id}.log")
        _put_info(submission_id, {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "status": PENDING,
            "metadata": metadata or {},
            "submit_time": time.time(),
            "log_path": log_path,
        })
        env_vars = dict((runtime_env or {}).get("env_vars", {}))
        Supervisor = ray_tpu.remote(JobSupervisor)
        actor = Supervisor.options(
            name=f"_job_supervisor:{submission_id}",
            lifetime="detached").remote(
                submission_id, entrypoint, metadata or {}, env_vars,
                log_path)
        # fire-and-forget: wait() runs on the actor until the job exits
        actor.wait.remote()
        return submission_id

    def get_job_status(self, submission_id: str) -> Optional[str]:
        info = _get_info(submission_id)
        return info["status"] if info else None

    def get_job_info(self, submission_id: str) -> Optional[Dict[str, Any]]:
        return _get_info(submission_id)

    def get_job_logs(self, submission_id: str) -> str:
        info = _get_info(submission_id)
        if info is None:
            raise ValueError(f"no job {submission_id!r}")
        try:
            with open(info["log_path"], "rb") as f:
                return f.read().decode(errors="replace")
        except FileNotFoundError:
            return ""

    def stop_job(self, submission_id: str) -> bool:
        info = _get_info(submission_id)
        if info is None or info["status"] in TERMINAL:
            return False
        try:
            actor = ray_tpu.get_actor(
                f"_job_supervisor:{submission_id}")
            return ray_tpu.get(actor.stop.remote(), timeout=30)
        except ValueError:
            return False

    def list_jobs(self) -> List[Dict[str, Any]]:
        core = _kv()
        out = []
        for key in core.kv_keys(prefix="info:", namespace=JOB_KV_NS):
            blob = core.kv_get(key, namespace=JOB_KV_NS)
            if blob:
                out.append(json.loads(blob))
        return sorted(out, key=lambda j: j.get("submit_time", 0))
