"""Job submission (reference ``dashboard/modules/job/``)."""

from ray_tpu.job.job_manager import (  # noqa: F401
    FAILED,
    PENDING,
    RUNNING,
    STOPPED,
    SUCCEEDED,
    JobManager,
    JobSupervisor,
)
from ray_tpu.job.sdk import JobSubmissionClient  # noqa: F401
