"""Job submission SDK.

Parity: reference ``dashboard/modules/job/sdk.py``
(``JobSubmissionClient``:40) — a thin HTTP client over the dashboard's
job REST endpoints.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional

from ray_tpu.job.job_manager import TERMINAL


class JobSubmissionClient:
    def __init__(self, address: str = "http://127.0.0.1:8265"):
        self.address = address.rstrip("/")

    def _request(self, method: str, path: str,
                 body: Optional[Dict[str, Any]] = None):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            self.address + path, data=data, method=method,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            detail = e.read().decode(errors="replace")
            raise RuntimeError(f"{method} {path} -> {e.code}: {detail}")

    def submit_job(self, *, entrypoint: str,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   runtime_env: Optional[Dict[str, Any]] = None) -> str:
        reply = self._request("POST", "/api/jobs/", {
            "entrypoint": entrypoint, "submission_id": submission_id,
            "metadata": metadata, "runtime_env": runtime_env})
        return reply["submission_id"]

    def get_job_status(self, submission_id: str) -> str:
        return self._request("GET", f"/api/jobs/{submission_id}")["status"]

    def get_job_info(self, submission_id: str) -> Dict[str, Any]:
        return self._request("GET", f"/api/jobs/{submission_id}")

    def get_job_logs(self, submission_id: str) -> str:
        return self._request("GET",
                             f"/api/jobs/{submission_id}/logs")["logs"]

    def stop_job(self, submission_id: str) -> bool:
        return self._request("POST",
                             f"/api/jobs/{submission_id}/stop")["stopped"]

    def list_jobs(self) -> List[Dict[str, Any]]:
        return self._request("GET", "/api/jobs/")

    def wait_until_finished(self, submission_id: str,
                            timeout: float = 300.0) -> str:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            status = self.get_job_status(submission_id)
            if status in TERMINAL:
                return status
            time.sleep(0.5)
        raise TimeoutError(f"job {submission_id} still running after "
                           f"{timeout}s")
