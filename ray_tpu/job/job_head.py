"""Job-submission REST endpoints, mounted on the dashboard.

Parity: reference ``dashboard/modules/job/job_head.py:145`` — POST
/api/jobs/ submits, GET /api/jobs/ lists, GET /api/jobs/{id} status,
GET /api/jobs/{id}/logs, POST /api/jobs/{id}/stop.
"""

from __future__ import annotations

import asyncio
from typing import Any, Dict

from aiohttp import web

from ray_tpu.job.job_manager import JobManager

_manager = JobManager()


async def _call(fn, *args, **kwargs):
    return await asyncio.get_running_loop().run_in_executor(
        None, lambda: fn(*args, **kwargs))


async def submit(request: web.Request) -> web.Response:
    body: Dict[str, Any] = await request.json()
    if "entrypoint" not in body:
        return web.json_response({"error": "entrypoint required"},
                                 status=400)
    try:
        sid = await _call(_manager.submit_job,
                          entrypoint=body["entrypoint"],
                          submission_id=body.get("submission_id"),
                          metadata=body.get("metadata"),
                          runtime_env=body.get("runtime_env"))
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=400)
    return web.json_response({"submission_id": sid})


async def list_jobs(request: web.Request) -> web.Response:
    return web.json_response(await _call(_manager.list_jobs))


async def status(request: web.Request) -> web.Response:
    info = await _call(_manager.get_job_info,
                       request.match_info["submission_id"])
    if info is None:
        return web.json_response({"error": "not found"}, status=404)
    return web.json_response(info)


async def logs(request: web.Request) -> web.Response:
    try:
        text = await _call(_manager.get_job_logs,
                           request.match_info["submission_id"])
    except ValueError as e:
        return web.json_response({"error": str(e)}, status=404)
    return web.json_response({"logs": text})


async def stop(request: web.Request) -> web.Response:
    ok = await _call(_manager.stop_job,
                     request.match_info["submission_id"])
    return web.json_response({"stopped": bool(ok)})


def add_job_routes(app: web.Application) -> None:
    app.router.add_post("/api/jobs/", submit)
    app.router.add_get("/api/jobs/", list_jobs)
    app.router.add_get("/api/jobs/{submission_id}", status)
    app.router.add_get("/api/jobs/{submission_id}/logs", logs)
    app.router.add_post("/api/jobs/{submission_id}/stop", stop)
