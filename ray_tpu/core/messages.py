"""Typed control-plane message schemas.

Parity: reference ``src/ray/protobuf/*.proto`` — every cross-process
message has a declared shape, and a frame from an incompatible peer is
rejected AT THE BOUNDARY with a structured error instead of failing
somewhere inside unpickling.  Two layers:

1. **Frame versioning** (``rpc.py``): the version byte rides the frame
   HEADER, outside the pickled payload, so a mismatched frame is refused
   before any payload bytes are interpreted.
2. **Schema registry** (this module): core RPC methods declare required
   fields (+ optional type constraints); ``validate`` runs in
   ``Server.dispatch`` and turns a malformed payload into a
   ``SchemaError`` naming the method and field.

The registry covers the control-plane surface whose corruption is
hardest to debug (registration, leases, task/actor pushes, object
plane); unregistered methods pass through — adding a schema is one
line, not a migration.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

__all__ = ["SchemaError", "register_schema", "validate", "SCHEMAS"]


class SchemaError(Exception):
    """A message failed boundary validation (method + field in text)."""


#: method -> {field: expected_type_or_None}; None = presence only
SCHEMAS: Dict[str, Dict[str, Optional[type]]] = {}


def register_schema(method: str, **fields: Optional[type]) -> None:
    SCHEMAS[method] = fields


def validate(method: str, data: Any) -> None:
    """Raise SchemaError if ``data`` violates the method's schema."""
    schema = SCHEMAS.get(method)
    if schema is None:
        return
    if not isinstance(data, dict):
        raise SchemaError(
            f"{method}: payload must be a dict, got {type(data).__name__}")
    for field, expected in schema.items():
        if field not in data:
            raise SchemaError(f"{method}: missing required field {field!r}")
        if expected is not None and data[field] is not None \
                and not isinstance(data[field], expected):
            raise SchemaError(
                f"{method}: field {field!r} must be "
                f"{getattr(expected, '__name__', expected)}, got "
                f"{type(data[field]).__name__}")


# -- core control-plane schemas ------------------------------------------
# registration / membership
register_schema("register_node", node_id=bytes, raylet_address=None,
                resources=dict)
register_schema("register_worker", worker_id=bytes, pid=int,
                task_address=None)
register_schema("register_job", driver_address=None)
register_schema("reattach_job", job_id=bytes)
register_schema("health_report", node_id=bytes, resources_available=dict)

# leases / scheduling
register_schema("request_worker_lease", resources=dict)
register_schema("cancel_lease", token=str)
register_schema("return_worker", worker_id=bytes)
register_schema("lease_worker_for_actor", actor_id=bytes, resources=dict,
                spec_blob=bytes)

# task / actor execution
register_schema("push_task", spec_blob=bytes)
register_schema("push_tasks", specs_blob=bytes)
register_schema("cancel_task", task_id=bytes)
register_schema("create_actor", spec_blob=bytes)
register_schema("push_actor_task", spec_blob=bytes)
register_schema("push_actor_tasks", specs_blob=bytes)
register_schema("register_actor", actor_id=bytes, spec_blob=bytes,
                resources=dict, job_id=bytes)
register_schema("actor_started", actor_id=bytes, task_address=None)
register_schema("kill_actor", actor_id=bytes)

# object plane
register_schema("object_create", object_id=bytes, size=int)
register_schema("object_seal", object_id=bytes)
register_schema("object_get", object_ids=list)
register_schema("object_release", object_ids=list)
register_schema("object_free", object_ids=list)
register_schema("get_small_object", object_id=bytes)
# node-to-node transfer protocol (raylet <-> raylet)
register_schema("object_pull_start", object_id=bytes)
register_schema("object_pull_chunk", object_id=bytes, offset=int, n=int)
register_schema("object_pull_end", object_id=bytes)
# owner-side object directory updates (raylet -> owner worker)
register_schema("object_location_added", object_id=bytes, node=None)
register_schema("object_location_removed", object_id=bytes, node=None)

# telemetry pipeline
register_schema("report_metrics", records=list)
register_schema("report_spans", spans=list)

# kv / functions / pubsub
register_schema("kv_put", key=str, value=None)
register_schema("kv_get", key=str)
register_schema("kv_del", key=str)
register_schema("get_function", function_id=str)
register_schema("register_function", function_id=str, blob=bytes)
register_schema("subscribe", channel=str)
register_schema("unsubscribe", channel=str)

# placement groups
register_schema("create_placement_group", pg_id=bytes, bundles=list)
register_schema("remove_placement_group", pg_id=bytes)
