"""Typed control-plane message schemas.

Parity: reference ``src/ray/protobuf/*.proto`` — every cross-process
message has a declared shape, and a frame from an incompatible peer is
rejected AT THE BOUNDARY with a structured error instead of failing
somewhere inside unpickling.  Two layers:

1. **Frame versioning** (``rpc.py``): the version byte rides the frame
   HEADER, outside the pickled payload, so a mismatched frame is refused
   before any payload bytes are interpreted.
2. **Schema registry** (this module): core RPC methods declare required
   fields (+ optional type constraints); ``validate`` runs in
   ``Server.dispatch`` and turns a malformed payload into a
   ``SchemaError`` naming the method and field.

The registry covers the control-plane surface whose corruption is
hardest to debug (registration, leases, task/actor pushes, object
plane); unregistered methods pass through — adding a schema is one
line, not a migration.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

__all__ = ["SchemaError", "Opt", "register_schema", "validate", "SCHEMAS"]


class SchemaError(Exception):
    """A message failed boundary validation (method + field in text)."""


class Opt:
    """Marks a schema field as optional: absent or None passes; when
    present and non-None, the wrapped type (if any) is enforced."""

    __slots__ = ("type",)

    def __init__(self, type_: Optional[type] = None):
        self.type = type_


#: method -> {field: expected_type | None (presence only) | Opt(...)}
SCHEMAS: Dict[str, Dict[str, Any]] = {}


def register_schema(method: str, **fields: Any) -> None:
    SCHEMAS[method] = fields


def _type_ok(value: Any, expected: type) -> bool:
    """isinstance with JSON-ish numerics: a float field accepts an int
    (handlers coerce with float(...)), but bool never passes for a
    numeric field."""
    if expected is float:
        return isinstance(value, (int, float)) \
            and not isinstance(value, bool)
    return isinstance(value, expected)


def validate(method: str, data: Any) -> None:
    """Raise SchemaError if ``data`` violates the method's schema."""
    schema = SCHEMAS.get(method)
    if schema is None:
        return
    if not isinstance(data, dict):
        # payload-free methods (pure reads like get_nodes/clock_sync)
        # accept the conventional ``None`` body.  Methods with Opt
        # fields still require a dict: their handlers index into the
        # payload, so letting None through would trade this structured
        # error for an AttributeError inside the handler.
        if data is None and not schema:
            return
        raise SchemaError(
            f"{method}: payload must be a dict, got {type(data).__name__}")
    for field, expected in schema.items():
        if isinstance(expected, Opt):
            value = data.get(field)
            if value is not None and expected.type is not None \
                    and not _type_ok(value, expected.type):
                raise SchemaError(
                    f"{method}: optional field {field!r} must be "
                    f"{expected.type.__name__}, got {type(value).__name__}")
            continue
        if field not in data:
            raise SchemaError(f"{method}: missing required field {field!r}")
        if expected is not None and data[field] is not None \
                and not _type_ok(data[field], expected):
            raise SchemaError(
                f"{method}: field {field!r} must be "
                f"{getattr(expected, '__name__', expected)}, got "
                f"{type(data[field]).__name__}")


# -- core control-plane schemas ------------------------------------------
# registration / membership
register_schema("register_node", node_id=bytes, raylet_address=None,
                resources=dict, pid=Opt(int))
register_schema("register_worker", worker_id=bytes, pid=int,
                task_address=None)
register_schema("register_job", driver_address=None)
register_schema("reattach_job", job_id=bytes)
register_schema("health_report", node_id=bytes, resources_available=dict)

# leases / scheduling
register_schema("request_worker_lease", resources=dict, trace=Opt(dict))
register_schema("cancel_lease", token=str)
register_schema("return_worker", worker_id=bytes)
register_schema("lease_worker_for_actor", actor_id=bytes, resources=dict,
                spec_blob=bytes)
# batched bring-up: one RPC leases workers + pushes creation tasks for a
# whole group of actors bound for this node (GCS -> raylet fan-out)
register_schema("lease_workers_for_actors", actors=list)

# task / actor execution
register_schema("push_task", spec_blob=bytes)
register_schema("push_tasks", specs_blob=bytes)
register_schema("cancel_task", task_id=bytes)
register_schema("create_actor", spec_blob=bytes)
register_schema("push_actor_task", spec_blob=bytes)
register_schema("push_actor_tasks", specs_blob=bytes)
register_schema("register_actor", actor_id=bytes, spec_blob=bytes,
                resources=dict, job_id=bytes, strategy=Opt(str),
                strategy_node=Opt(str), strategy_soft=Opt(bool))
# coalesced registration: ``actors`` is a list of register_actor
# payloads; idempotent keyed on each entry's actor_id so a retried
# batch converges on ONE directory entry per actor
register_schema("register_actor_batch", actors=list)
register_schema("actor_started", actor_id=bytes, task_address=None)
register_schema("kill_actor", actor_id=bytes)

# object plane
register_schema("object_create", object_id=bytes, size=int)
register_schema("object_seal", object_id=bytes)
register_schema("object_get", object_ids=list)
register_schema("object_release", object_ids=list)
register_schema("object_free", object_ids=list)
register_schema("get_small_object", object_id=bytes)
# node-to-node transfer protocol (raylet <-> raylet)
register_schema("object_pull_start", object_id=bytes)
register_schema("object_pull_chunk", object_id=bytes, offset=int, n=int)
register_schema("object_pull_end", object_id=bytes)
# owner-side object directory updates (raylet -> owner worker)
register_schema("object_location_added", object_id=bytes, node=None)
register_schema("object_location_removed", object_id=bytes, node=None)

# telemetry pipeline
register_schema("report_metrics", records=list)
register_schema("report_spans", spans=list)
register_schema("clock_sync")
register_schema("get_metrics")
register_schema("get_spans", cat=Opt(str), limit=Opt(int))

# metrics history + alerting plane (core/metrics_history.py)
register_schema("get_timeseries", series=Opt(str), since=Opt(float),
                limit=Opt(int))
register_schema("get_alerts")
register_schema("healthz")

# distributed tracing plane (core/tracing.py -> GCS trace ring)
register_schema("report_trace_spans", spans=list)
register_schema("get_trace", trace_id=str)
register_schema("list_traces", deployment=Opt(str), slo_misses=Opt(bool),
                since=Opt(float), until=Opt(float), limit=Opt(int))

# continuous profiling plane (core/profiler.py)
register_schema("report_profile", records=list)
register_schema("get_profile", job=Opt(str), node=Opt(str),
                since=Opt(float), limit=Opt(int))
register_schema("profiler_control", enabled=bool, hz=Opt(float),
                duration_s=Opt(float))

# introspection / state surface (payload-free or optional-only reads)
register_schema("ping")
register_schema("debug_state")          # served by both GCS and raylet
# GCS restart-recovery snapshot: what the WAL/snapshot restored and how
# far the live reconvergence (node re-registration, restored-actor
# revalidation) has progressed — consumed by `ray-tpu status`, the HA
# bench, and tests/test_gcs_ha.py
register_schema("recovery_state")
register_schema("get_nodes")
register_schema("get_cluster_load")
register_schema("get_cluster_stats")
register_schema("list_jobs")
register_schema("list_actors")
register_schema("list_placement_groups")
register_schema("list_workers")
register_schema("list_events", limit=Opt(int), severity=Opt(str))
# incident forensics plane (core/flight_recorder.py + GCS journal)
register_schema("report_flight_tail", source=str, pid=int, frames=list,
                reason=Opt(str), node_id=Opt(bytes), torn=Opt(int))
register_schema("list_incidents", limit=Opt(int), kind=Opt(str))
register_schema("get_incident", incident_id=str)
register_schema("list_objects", limit=Opt(int))
register_schema("get_task_events", limit=Opt(int), job_id=Opt(str),
                state=Opt(str))
register_schema("store_info")
register_schema("store_stats")
register_schema("stack_trace")          # one worker's dump
register_schema("stack_traces")         # raylet fan-out over its workers
register_schema("kv_keys", prefix=Opt(str), namespace=Opt(str))

# job / node lifecycle
register_schema("job_finished", job_id=bytes)
register_schema("drain_node", node_id=bytes, reason=Opt(str),
                force=Opt(bool))
# graceful drain (GCS -> raylet): migrate sealed primaries + spill
# blobs to the listed ACTIVE peers, then stop taking leases for good
register_schema("drain", peers=list, reason=Opt(str))
# drain migration (raylet -> peer raylet): pull this object from me (or
# my spill tier) and become its primary holder before I release
register_schema("adopt_object", object_id=bytes, owner=Opt(list),
                source=Opt(list), size=Opt(int), spilled=Opt(bool))
# per-job scheduling quotas (weights + in-flight ceilings)
register_schema("set_job_quota", job=str, quota=Opt(dict))
register_schema("get_job_quotas")

# actor lifecycle (beyond registration)
register_schema("actor_creation_failed", actor_id=bytes, reason=Opt(str))
register_schema("get_actor", actor_id=Opt(bytes), name=Opt(str),
                namespace=Opt(str))

# pubsub fan-in
register_schema("publish", channel=str, message=None)

# placement-group internals (GCS <-> raylet two-phase commit, client poll)
register_schema("placement_group_ready", pg_id=bytes, block_s=Opt(float))
register_schema("prepare_bundle", pg_id=bytes, bundle_index=int,
                resources=dict)
register_schema("commit_bundle", pg_id=bytes, bundle_index=int)
register_schema("return_bundle", pg_id=bytes, bundle_index=int)

# object plane: owner-side directory / recovery / borrow tracking
register_schema("reconstruct_object", object_id=bytes)
register_schema("get_object_locations", object_id=bytes)
register_schema("object_spilled", object_id=bytes, uri=Opt(str),
                node=Opt(list))
register_schema("object_contains", object_id=bytes)
register_schema("add_borrow", object_id=bytes, borrower=None)
register_schema("remove_borrow", object_id=bytes, borrower=None)
register_schema("report_task_events", events=list)

# kv / functions / pubsub
register_schema("kv_put", key=str, value=None)
register_schema("kv_get", key=str)
register_schema("kv_del", key=str)
register_schema("get_function", function_id=str)
register_schema("register_function", function_id=str, blob=bytes)
register_schema("subscribe", channel=str)
register_schema("unsubscribe", channel=str)

# placement groups
register_schema("create_placement_group", pg_id=bytes, bundles=list)
register_schema("remove_placement_group", pg_id=bytes)
