"""Metrics history + SLO alerting: the cluster health plane's engine.

The GCS metrics table (``gcs.py::_ingest_metrics``) is point-in-time:
one merged value per ``(name, tags)`` series.  This module gives it a
past and a judgement:

* **History rings** — every sample tick (``metrics_history_interval_s``)
  the engine folds the merged table into per-series ring buffers
  bounded to ``metrics_history_window_s / metrics_history_interval_s``
  points.  Counters are stored as **per-tick deltas** (reset-safe), so
  rates fall out of a window sum; gauges store raw values; histograms
  store per-tick ``(count, sum, buckets)`` deltas so windowed quantiles
  fall out of a bucket merge.  Eviction is accounted
  (``ray_tpu_metrics_history_evicted_total``) exactly like the trace /
  profile rings — memory is provably ``series x window/interval``
  points, never more.

* **Recording rules** — named derived signals re-evaluated each tick
  from the rings (rate-over-window, histogram-quantile, sum/max of
  gauges) and appended to their own rings, so consumers (``ray-tpu
  top`` sparklines, ``/api/timeseries``, the ROADMAP item-5 node
  autoscaler) subscribe to *signals*, not raw series.  The built-in
  set covers exactly the autoscaler's inputs: pending-lease backlog,
  arena occupancy, serve request rate / p99 / shed rate, heartbeat
  miss rate, GCS persist failures.

* **Alert rules** — threshold and SLO burn-rate rules with
  ``for:``-duration hysteresis on both edges: a condition must hold
  ``for_s`` before ``pending -> firing``, and clear continuously for
  ``resolve_for_s`` before ``firing -> resolved`` (flaps die in
  ``pending``).  Transitions are returned to the caller (the GCS
  publishes them on the ``alerts`` pubsub channel and persists the
  firing set), and a firing alert restored after a GCS restart re-fires
  or resolves through the same machinery — never silently vanishes.

Static analysis: ``rtpu-check``'s ``metric-drift`` rule reads the
``RecordingRule(source=...)`` / ``AlertRule(signal=..., source=...)``
constructor calls below and requires every referenced ``ray_tpu_*``
series to exist in ``scripts/metrics_golden.txt`` (and every derived
signal to be defined by a RecordingRule), so a renamed producer cannot
leave a rule silently evaluating a series that no longer exists.

No asyncio in here: the engine is a pure state machine driven by the
GCS's ``_history_loop`` with explicit ``now`` timestamps, which is what
makes the hysteresis matrix unit-testable with a fake clock.
"""

from __future__ import annotations

import bisect
import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

__all__ = ["RecordingRule", "AlertRule", "MetricsHistory",
           "default_recording_rules", "default_alert_rules"]


# ---------------------------------------------------------------------------
# rule definitions (declarative: the metric-drift rule reads these)
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RecordingRule:
    """One derived signal: ``name`` is re-computed each tick from the
    ``source`` series' rings and appended to its own ring."""

    name: str            # derived series, e.g. "serve:p99_latency_s"
    source: str          # ray_tpu_* series the rule reads
    fn: str              # rate | quantile | sum | max | avg | burn
    window_s: float = 60.0
    q: float = 0.99
    #: tag keys preserved in the derived series (one derived ring per
    #: distinct projection, e.g. per deployment); () = one global ring
    group_by: Tuple[str, ...] = ()


@dataclass(frozen=True)
class AlertRule:
    """Threshold or SLO burn-rate rule with two-sided hysteresis."""

    name: str
    #: derived-signal (RecordingRule) or raw gauge series to compare;
    #: unused by kind="slo_burn" rules (they read ``source`` directly)
    signal: str = ""
    op: str = ">"
    threshold: float = 0.0
    #: the condition must hold this long before pending -> firing
    for_s: float = 10.0
    #: ... and clear continuously this long before firing -> resolved
    resolve_for_s: float = 10.0
    severity: str = "warning"  # warning | critical
    description: str = ""
    kind: str = "threshold"    # threshold | slo_burn
    #: slo_burn: latency histogram whose over-SLO mass is the burn input
    source: str = ""
    window_s: float = 60.0
    group_by: Tuple[str, ...] = ()


def default_recording_rules(interval_s: float) -> List[RecordingRule]:
    """The built-in signal set.  Window spans at least a few sample
    ticks so one missed flush doesn't zero a rate."""
    w = max(60.0, 4 * interval_s)
    return [
        # -- the item-5 node autoscaler's subscription points ----------
        RecordingRule(name="cluster:pending_leases",
                      source="ray_tpu_sched_pending_leases", fn="sum"),
        RecordingRule(name="cluster:arena_occupancy",
                      source="ray_tpu_arena_occupancy_fraction",
                      fn="max"),
        # -- serve SLO plane -------------------------------------------
        RecordingRule(name="serve:request_rate",
                      source="ray_tpu_serve_request_latency_s",
                      fn="rate", window_s=w, group_by=("deployment",)),
        RecordingRule(name="serve:p99_latency_s",
                      source="ray_tpu_serve_request_latency_s",
                      fn="quantile", q=0.99, window_s=w,
                      group_by=("deployment",)),
        RecordingRule(name="serve:shed_rate",
                      source="ray_tpu_serve_shed_total", fn="rate",
                      window_s=w, group_by=("deployment",)),
        RecordingRule(name="serve:queue_depth",
                      source="ray_tpu_serve_queue_depth", fn="sum",
                      group_by=("deployment",)),
        # burn rate as a first-class series: the EXACT input the
        # ServeSLOBurnRate alert compares against 1.0, exposed through
        # get_timeseries so the autoscaler can scale up at burn ~0.5 —
        # before the alert's threshold is ever reached
        RecordingRule(name="serve:slo_burn_rate",
                      source="ray_tpu_serve_request_latency_s",
                      fn="burn", window_s=w, group_by=("deployment",)),
        # -- control-plane health --------------------------------------
        RecordingRule(name="gcs:heartbeat_miss_rate",
                      source="ray_tpu_gcs_heartbeat_misses_total",
                      fn="rate", window_s=w),
        RecordingRule(name="gcs:persist_failure_rate",
                      source="ray_tpu_gcs_persist_failures_total",
                      fn="rate", window_s=w),
        # -- device plane (PR 18) --------------------------------------
        # compile rate as a series: the RecompileStorm alert's input
        # (threshold alerts read gauges/derived series, not counters) —
        # steady state is 0; warmup shows one burst then decays
        RecordingRule(name="device:compile_rate",
                      source="ray_tpu_xla_compiles_total",
                      fn="rate", window_s=w),
        RecordingRule(name="train:mfu",
                      source="ray_tpu_train_mfu", fn="max"),
        RecordingRule(name="train:step_data_wait_frac",
                      source="ray_tpu_train_step_data_wait_frac",
                      fn="max"),
        RecordingRule(name="serve:decode_device_frac",
                      source="ray_tpu_serve_decode_device_frac",
                      fn="max", group_by=("deployment",)),
    ]


def default_alert_rules(interval_s: float) -> List[AlertRule]:
    """Built-in alert set.  The serve burn rule's ``for_s`` spans two
    evaluation intervals, so a sustained SLO barrage fires within
    three ticks (the e2e gate) while a single slow flush cannot."""
    return [
        AlertRule(name="ServeSLOBurnRate", kind="slo_burn",
                  source="ray_tpu_serve_request_latency_s",
                  threshold=1.0, for_s=2 * interval_s,
                  resolve_for_s=2 * interval_s, severity="critical",
                  window_s=max(5.0, 10 * interval_s),
                  group_by=("deployment",),
                  description="fraction of serve requests over "
                              "serve_slo_latency_s is burning the "
                              "error budget (burn rate > 1 sustains "
                              "an SLO violation)"),
        AlertRule(name="ServeShedRate", signal="serve:shed_rate",
                  op=">", threshold=0.5, for_s=15.0,
                  resolve_for_s=30.0, severity="warning",
                  group_by=("deployment",),
                  description="requests are being shed (429) at a "
                              "sustained rate: the deployment is "
                              "under-provisioned for its load"),
        AlertRule(name="HeartbeatMissRate",
                  signal="gcs:heartbeat_miss_rate", op=">",
                  threshold=0.2, for_s=15.0, resolve_for_s=30.0,
                  severity="warning",
                  description="raylet health reports are failing: "
                              "nodes are at risk of being declared "
                              "dead"),
        AlertRule(name="ArenaPressure",
                  signal="cluster:arena_occupancy", op=">",
                  threshold=0.9, for_s=15.0, resolve_for_s=30.0,
                  severity="warning",
                  description="an object-store arena is nearly full; "
                              "creates will soon spill reactively or "
                              "fail"),
        AlertRule(name="GcsPersistFailures",
                  signal="gcs:persist_failure_rate", op=">",
                  threshold=0.0, for_s=0.0, resolve_for_s=60.0,
                  severity="critical",
                  description="GCS table snapshot writes are failing: "
                              "durability is degraded to the WAL (or "
                              "nothing)"),
        # -- device plane (PR 18) --------------------------------------
        # steady-state steps must not compile: a sustained compile rate
        # means shapes keep missing the padding buckets (a shape leak),
        # collapsing device throughput while host metrics look healthy.
        # for_s spans two ticks (the ServeSLOBurnRate fires-within-
        # three-ticks discipline); resolves once shapes stabilize.
        AlertRule(name="RecompileStorm",
                  signal="device:compile_rate", op=">",
                  threshold=0.5, for_s=2 * interval_s,
                  resolve_for_s=2 * interval_s, severity="warning",
                  description="XLA keeps compiling during steady-state "
                              "stepping: input shapes are leaking past "
                              "the padding buckets and every retrace "
                              "stalls the device"),
        # persistent rank skew gates every gang step on the slowest
        # member; group_by includes the straggler tag so the alert
        # NAMES the slow rank
        AlertRule(name="GangStraggler",
                  signal="ray_tpu_gang_rank_skew_seconds", op=">",
                  threshold=0.05, for_s=2 * interval_s,
                  resolve_for_s=2 * interval_s, severity="warning",
                  group_by=("deployment", "straggler"),
                  description="one rank of a sharded gang is "
                              "persistently slower than its peers; "
                              "every decode step waits for it (the "
                              "straggler tag names the rank)"),
    ]


# ---------------------------------------------------------------------------
# series rings
# ---------------------------------------------------------------------------

class _Ring:
    """One series' bounded history.  ``kind`` decides the point shape:
    counter points are per-tick deltas, gauge/derived points raw
    values, histogram points ``(count_d, sum_d, buckets_d)`` tuples."""

    __slots__ = ("kind", "points", "last_raw", "last_sum", "last_count",
                 "last_buckets", "boundaries", "last_ts")

    def __init__(self, kind: str):
        self.kind = kind
        self.points: deque = deque()  # (ts, value)
        self.last_raw = 0.0     # counters: last cumulative seen
        self.last_sum = 0.0     # histograms: last cumulative sum/count
        self.last_count = 0
        self.last_buckets: Optional[List[float]] = None
        self.boundaries: Optional[List[float]] = None
        self.last_ts = 0.0


class _AlertState:
    __slots__ = ("state", "since", "pending_since", "clear_since",
                 "value", "restored", "severity")

    def __init__(self):
        self.state = "inactive"  # inactive | pending | firing
        self.since = 0.0         # when the current state was entered
        self.pending_since = 0.0
        self.clear_since: Optional[float] = None
        self.value: Optional[float] = None
        self.restored = False    # firing state carried over a restart
        self.severity = "warning"


def _cmp(value: float, op: str, threshold: float) -> bool:
    return value > threshold if op == ">" else value < threshold


class MetricsHistory:
    """Bounded time-series rings + recording rules + alert evaluator.

    Driven by the GCS: ``sample(table, now)`` each tick, then
    ``evaluate(now)``; both take explicit timestamps so tests drive a
    fake clock.  Memory bound: ``capacity`` points per series ring,
    rings for series that stopped appearing are swept after two
    windows, and every overwritten point increments ``evicted_total``.
    """

    def __init__(self, interval_s: float, window_s: float, *,
                 slo_latency_s: float = 0.0,
                 slo_error_budget: float = 0.01,
                 recording_rules: Optional[List[RecordingRule]] = None,
                 alert_rules: Optional[List[AlertRule]] = None,
                 restored_firing: Optional[List[Dict[str, Any]]] = None):
        self.interval_s = max(0.05, float(interval_s))
        self.window_s = max(self.interval_s * 2, float(window_s))
        self.capacity = max(2, int(round(self.window_s / self.interval_s)))
        self.slo_latency_s = float(slo_latency_s)
        self.slo_error_budget = max(1e-6, float(slo_error_budget))
        self.recording_rules = (default_recording_rules(self.interval_s)
                                if recording_rules is None
                                else list(recording_rules))
        rules = (default_alert_rules(self.interval_s)
                 if alert_rules is None else list(alert_rules))
        self.alert_rules: Dict[str, AlertRule] = {r.name: r for r in rules}
        self._rings: Dict[Tuple[str, Tuple], _Ring] = {}
        self._alerts: Dict[Tuple[str, Tuple], _AlertState] = {}
        #: recently-resolved alerts, newest last (bounded)
        self.resolved: deque = deque(maxlen=64)
        self.evicted_total = 0
        self.samples_total = 0
        self.sample_failures = 0
        # firing state persisted by the previous GCS incarnation: seed
        # the machine as FIRING so the alert is visible immediately and
        # either re-confirms from fresh samples or resolves through the
        # normal hysteresis — a restart can never silently lose it
        for rec in restored_firing or []:
            rule = self.alert_rules.get(rec.get("rule", ""))
            if rule is None:
                continue
            key = (rule.name,
                   tuple(sorted((rec.get("tags") or {}).items())))
            st = self._alerts[key] = _AlertState()
            st.state = "firing"
            st.since = float(rec.get("since", 0.0))
            st.value = rec.get("value")
            st.restored = True
            st.severity = rule.severity

    # -- sampling ------------------------------------------------------
    def _append(self, ring: _Ring, ts: float, value: Any) -> None:
        if len(ring.points) >= self.capacity:
            ring.points.popleft()
            self.evicted_total += 1
        ring.points.append((ts, value))
        ring.last_ts = ts

    def sample(self, table: Dict[Any, Dict[str, Any]], now: float) -> None:
        """Fold one snapshot of the GCS merged-metrics table into the
        rings.  ``table`` is read-only here (the read handler is
        side-effect free too; pruning lives in the GCS sweep)."""
        self.samples_total += 1
        for key, rec in table.items():
            name, tags = key[0], key[1]
            rkey = (name, tags)
            kind = rec.get("type")
            ring = self._rings.get(rkey)
            if ring is None:
                ring = self._rings[rkey] = _Ring(kind)
            if kind == "counter":
                value = float(rec.get("value", 0.0))
                delta = value - ring.last_raw
                if delta < 0:  # producer restarted: the value IS the delta
                    delta = value
                ring.last_raw = value
                self._append(ring, now, delta)
            elif kind == "gauge":
                self._append(ring, now, float(rec.get("value", 0.0)))
            elif kind == "histogram":
                buckets = list(rec.get("buckets") or [])
                count = int(rec.get("count", 0))
                total = float(rec.get("sum", 0.0))
                last_b = ring.last_buckets
                if last_b is None or len(last_b) != len(buckets) \
                        or count < ring.last_count:
                    bucket_d = list(buckets)
                    count_d, sum_d = count, total
                else:
                    bucket_d = [b - a for a, b in zip(last_b, buckets)]
                    count_d = count - ring.last_count
                    sum_d = total - ring.last_sum
                ring.last_buckets = buckets
                ring.last_count = count
                ring.last_sum = total
                ring.boundaries = list(rec.get("boundaries") or [])
                self._append(ring, now, (count_d, sum_d, bucket_d))
            else:
                continue
        # sweep rings whose series left the table (pruned gauges, dead
        # processes): after two windows without a sample they free
        for rkey, ring in list(self._rings.items()):
            if now - ring.last_ts > 2 * self.window_s:
                del self._rings[rkey]
        self._run_recording_rules(now)

    def observe(self, name: str, value: float, now: float,
                tags: Optional[Dict[str, str]] = None) -> None:
        """Direct gauge-style observation (the GCS pushes a few
        tick-local series — alive nodes, actors — that must not depend
        on any flush loop)."""
        rkey = (name, tuple(sorted((tags or {}).items())))
        ring = self._rings.get(rkey)
        if ring is None:
            ring = self._rings[rkey] = _Ring("gauge")
        self._append(ring, now, float(value))

    # -- windowed math -------------------------------------------------
    def _series(self, name: str) -> List[Tuple[Tuple, _Ring]]:
        return [(key[1], ring) for key, ring in self._rings.items()
                if key[0] == name]

    @staticmethod
    def _window_points(ring: _Ring, since: float):
        # half-open window (since, now]: a delta stamped exactly at the
        # window's left edge belongs to the PREVIOUS window.  Rings are
        # append-ordered; iterate from the right.
        out = []
        for ts, v in reversed(ring.points):
            if ts <= since:
                break
            out.append((ts, v))
        out.reverse()
        return out

    def rate(self, name: str, now: float, window_s: float,
             group: Optional[Dict[str, str]] = None) -> Optional[float]:
        """Sum of counter deltas over the window / window seconds,
        across every tagset of ``name`` whose tags contain ``group``
        (histogram points contribute their count delta)."""
        since = now - window_s
        total = 0.0
        seen = False
        for tags, ring in self._series(name):
            if ring.kind not in ("counter", "histogram"):
                continue
            if group and not (set(group.items()) <= set(tags)):
                continue
            for _ts, v in self._window_points(ring, since):
                total += v[0] if ring.kind == "histogram" else v
                seen = True
        if not seen:
            return None
        return total / window_s

    def _merged_hist_window(self, name: str, now: float, window_s: float,
                            group: Optional[Dict[str, str]] = None
                            ) -> Tuple[List[float], List[float], float]:
        """(boundaries, merged bucket deltas incl. +Inf, total count)
        of ``name`` over the window, restricted to rings whose tags
        contain ``group``."""
        since = now - window_s
        bounds: List[float] = []
        merged: List[float] = []
        total = 0.0
        for tags, ring in self._series(name):
            if ring.kind != "histogram" or not ring.boundaries:
                continue
            if group and not (set(group.items()) <= set(tags)):
                continue
            if not bounds:
                bounds = ring.boundaries
                merged = [0.0] * (len(bounds) + 1)
            if ring.boundaries != bounds:
                continue  # incompatible layout (renamed bounds): skip
            for _ts, (count_d, _sum_d, bucket_d) in \
                    self._window_points(ring, since):
                total += count_d
                for i, b in enumerate(bucket_d):
                    if i < len(merged):
                        merged[i] += b
        return bounds, merged, total

    def quantile(self, name: str, q: float, now: float, window_s: float,
                 group: Optional[Dict[str, str]] = None
                 ) -> Optional[float]:
        """Windowed histogram quantile (prometheus-style: linear
        interpolation inside the target bucket, upper bound for the
        overflow bucket)."""
        bounds, merged, total = self._merged_hist_window(
            name, now, window_s, group)
        if not bounds or total <= 0:
            return None
        target = q * total
        cum = 0.0
        for i, b in enumerate(merged):
            prev_cum = cum
            cum += b
            if cum >= target:
                if i >= len(bounds):  # overflow bucket: clamp
                    return bounds[-1]
                lo = bounds[i - 1] if i > 0 else 0.0
                hi = bounds[i]
                frac = (target - prev_cum) / b if b > 0 else 1.0
                return lo + (hi - lo) * frac
        return bounds[-1]

    def fraction_over(self, name: str, threshold: float, now: float,
                      window_s: float,
                      group: Optional[Dict[str, str]] = None
                      ) -> Optional[float]:
        """Fraction of windowed observations above ``threshold``
        (conservative: mass in buckets whose upper bound exceeds it)."""
        bounds, merged, total = self._merged_hist_window(
            name, now, window_s, group)
        if not bounds or total <= 0:
            return None
        idx = bisect.bisect_left(bounds, threshold)
        if idx >= len(bounds):
            over = merged[-1]  # only the overflow bucket can exceed
        else:
            over = sum(merged[idx + 1:])
            if bounds[idx] > threshold:
                # the threshold falls INSIDE this bucket: count its
                # whole mass as over (conservative — an SLO between
                # bounds can only over-report, never hide a burn)
                over += merged[idx]
        return over / total

    def latest(self, name: str, fn: str = "sum",
               group: Optional[Dict[str, str]] = None,
               now: Optional[float] = None) -> Optional[float]:
        """Latest-point aggregate of a gauge/derived series across
        matching tagsets (sum | max | avg).  With ``now``, rings that
        stopped updating (their series left the merged table — dead
        node, pruned stale gauge) drop out after ~3 missed ticks
        instead of contributing a ghost value for up to two windows
        (a dead node must not hold cluster:arena_occupancy high)."""
        stale_before = None if now is None else now - 3 * self.interval_s
        vals = []
        for tags, ring in self._series(name):
            if ring.kind not in ("gauge", "derived") or not ring.points:
                continue
            if stale_before is not None and ring.last_ts < stale_before:
                continue
            if group and not (set(group.items()) <= set(tags)):
                continue
            vals.append(ring.points[-1][1])
        if not vals:
            return None
        if fn == "max":
            return max(vals)
        if fn == "avg":
            return sum(vals) / len(vals)
        return sum(vals)

    # -- recording rules -----------------------------------------------
    def _groups_of(self, source: str, group_by: Tuple[str, ...]
                   ) -> List[Dict[str, str]]:
        if not group_by:
            return [{}]
        groups = []
        for tags, _ring in self._series(source):
            d = dict(tags)
            proj = {k: d[k] for k in group_by if k in d}
            if proj and proj not in groups:
                groups.append(proj)
        return groups

    def _run_recording_rules(self, now: float) -> None:
        for rule in self.recording_rules:
            for group in self._groups_of(rule.source, rule.group_by):
                value: Optional[float]
                if rule.fn == "rate":
                    value = self.rate(rule.source, now, rule.window_s,
                                      group or None)
                elif rule.fn == "quantile":
                    value = self.quantile(rule.source, rule.q, now,
                                          rule.window_s, group or None)
                elif rule.fn == "burn":
                    if self.slo_latency_s <= 0:
                        continue
                    miss = self.fraction_over(
                        rule.source, self.slo_latency_s, now,
                        rule.window_s, group or None)
                    value = (None if miss is None
                             else miss / self.slo_error_budget)
                else:
                    value = self.latest(rule.source, rule.fn,
                                        group or None, now=now)
                if value is None:
                    continue
                rkey = (rule.name, tuple(sorted(group.items())))
                ring = self._rings.get(rkey)
                if ring is None:
                    ring = self._rings[rkey] = _Ring("derived")
                self._append(ring, now, float(value))

    # -- alert evaluation ----------------------------------------------
    def _signal_value(self, rule: AlertRule, group: Dict[str, str],
                      now: float) -> Optional[float]:
        if rule.kind == "slo_burn":
            if self.slo_latency_s <= 0:
                return None
            miss = self.fraction_over(rule.source, self.slo_latency_s,
                                      now, rule.window_s, group or None)
            if miss is None:
                return None
            return miss / self.slo_error_budget
        return self.latest(rule.signal, "max", group or None, now=now)

    def evaluate(self, now: float) -> List[Dict[str, Any]]:
        """One evaluation tick over every rule x live tag group.
        Returns the state TRANSITIONS (pending->firing,
        firing->resolved, restored->firing/resolved) for the caller to
        publish; steady states return nothing."""
        transitions: List[Dict[str, Any]] = []
        for rule in self.alert_rules.values():
            source = rule.source if rule.kind == "slo_burn" \
                else rule.signal
            groups = self._groups_of(source, rule.group_by)
            # pending/firing (incl. restored) alerts may name groups
            # whose series vanished: keep evaluating them (condition
            # reads as no-data -> they resolve through hysteresis).
            # Inactive states are pruned below, so this cannot grow.
            for key, st in self._alerts.items():
                if key[0] == rule.name and st.state != "inactive":
                    g = dict(key[1])
                    if g not in groups:
                        groups.append(g)
            for group in groups:
                key = (rule.name, tuple(sorted(group.items())))
                st = self._alerts.get(key)
                if st is None:
                    st = self._alerts[key] = _AlertState()
                st.severity = rule.severity
                value = self._signal_value(rule, group, now)
                cond = value is not None and _cmp(value, rule.op,
                                                 rule.threshold)
                if value is not None:
                    st.value = value
                if st.state == "inactive":
                    if cond:
                        st.pending_since = now
                        if rule.for_s <= 0:
                            self._fire(rule, key, st, now, transitions)
                        else:
                            st.state = "pending"
                            st.since = now
                elif st.state == "pending":
                    if not cond:
                        st.state = "inactive"
                        st.since = now
                    elif now - st.pending_since >= rule.for_s:
                        self._fire(rule, key, st, now, transitions)
                elif st.state == "firing":
                    if cond:
                        if st.restored:
                            # restart survival: the condition still
                            # holds — announce the re-fire so no
                            # subscriber misses it
                            st.restored = False
                            transitions.append(self._event(
                                rule, key, st, "restored", "firing",
                                now))
                        st.clear_since = None
                    else:
                        if st.clear_since is None:
                            st.clear_since = now
                        if now - st.clear_since >= rule.resolve_for_s:
                            st.restored = False
                            st.state = "inactive"
                            resolved_at = now
                            self.resolved.append({
                                "rule": rule.name, "tags": dict(group),
                                "severity": rule.severity,
                                "value": st.value,
                                "since": st.since,
                                "resolved_at": resolved_at})
                            transitions.append(self._event(
                                rule, key, st, "firing", "resolved",
                                now))
                            st.since = now
                            st.clear_since = None
        # inactive states carry no memory (pending/firing are the only
        # states with history): drop them so deployment/group churn
        # cannot grow the table — alert-state memory stays bounded by
        # what is actually pending or firing
        for key in [k for k, st in self._alerts.items()
                    if st.state == "inactive"]:
            del self._alerts[key]
        return transitions

    def _fire(self, rule: AlertRule, key, st: _AlertState, now: float,
              transitions: List[Dict[str, Any]]) -> None:
        prev = st.state
        st.state = "firing"
        st.since = now
        st.clear_since = None
        transitions.append(self._event(rule, key, st, prev, "firing",
                                       now))

    def _event(self, rule: AlertRule, key, st: _AlertState,
               prev: str, new: str, now: float) -> Dict[str, Any]:
        return {"rule": rule.name, "tags": dict(key[1]),
                "from": prev, "to": new, "value": st.value,
                "severity": rule.severity, "ts": now,
                "description": rule.description}

    # -- views ----------------------------------------------------------
    def firing(self) -> List[Dict[str, Any]]:
        out = []
        for (name, tags), st in self._alerts.items():
            if st.state != "firing":
                continue
            rule = self.alert_rules.get(name)
            out.append({"rule": name, "tags": dict(tags),
                        "severity": st.severity, "value": st.value,
                        "since": st.since, "restored": st.restored,
                        "description": rule.description if rule else ""})
        out.sort(key=lambda a: a["since"])
        return out

    def export_firing(self) -> List[Dict[str, Any]]:
        """JSON-serializable firing set for restart persistence."""
        return [{"rule": a["rule"], "tags": a["tags"],
                 "severity": a["severity"], "value": a["value"],
                 "since": a["since"]} for a in self.firing()]

    def alerts_view(self) -> Dict[str, Any]:
        return {
            "firing": self.firing(),
            "resolved": list(self.resolved),
            "rules": [{"name": r.name, "kind": r.kind,
                       "signal": r.signal or r.source, "op": r.op,
                       "threshold": r.threshold, "for_s": r.for_s,
                       "resolve_for_s": r.resolve_for_s,
                       "severity": r.severity,
                       "description": r.description}
                      for r in self.alert_rules.values()],
        }

    def query(self, series: Optional[str] = None,
              since: Optional[float] = None,
              limit: int = 200) -> List[Dict[str, Any]]:
        """Ring contents for ``/api/timeseries`` / ``ray-tpu top``.
        ``series``: exact name, or a prefix ending in ``*``.  Histogram
        rings serve their per-tick count deltas (quantiles are served
        via the derived recording-rule series)."""
        prefix = None
        if series and series.endswith("*"):
            prefix = series[:-1]
        out = []
        for (name, tags), ring in self._rings.items():
            if series is not None:
                if prefix is not None:
                    if not name.startswith(prefix):
                        continue
                elif name != series:
                    continue
            pts = []
            for ts, v in ring.points:
                if since is not None and ts < since:
                    continue
                pts.append([ts, v[0] if ring.kind == "histogram" else v])
            out.append({"name": name, "tags": dict(tags),
                        "kind": ring.kind, "points": pts})
        # sort BEFORE applying the limit: under limit pressure the
        # caller gets a deterministic prefix, not whichever series
        # happened to sit first in ring-insertion order
        out.sort(key=lambda r: (r["name"], sorted(r["tags"].items())))
        return out[:limit]

    def stats(self) -> Dict[str, Any]:
        return {
            "series": len(self._rings),
            "points": sum(len(r.points) for r in self._rings.values()),
            "capacity_per_series": self.capacity,
            "evicted_total": self.evicted_total,
            "samples_total": self.samples_total,
            "sample_failures": self.sample_failures,
            "alerts_firing": sum(1 for s in self._alerts.values()
                                 if s.state == "firing"),
            "alerts_resolved_recent": len(self.resolved),
        }
