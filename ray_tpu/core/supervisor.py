"""Head-process supervision: auto-respawn a died GCS.

ROADMAP item 4 remainder: the HA control plane (PR 11) can recover a
restarted GCS — snapshot + WAL replay, idempotent registration replay,
jittered client reconnect — but *something* still had to perform the
restart, and until now that something was the test harness
(``Cluster.restart_head``).  :class:`HeadSupervisor` closes the loop
for driver-owned clusters: a daemon thread watches the head subprocess
(GCS + head raylet) and, when it exits unexpectedly, respawns it on
the SAME session dir and GCS port so every surviving raylet/worker
reconnects to the address it already knows and the PR-11 recovery
path takes over.

Respawns are bounded (``gcs_respawn_max`` per session, with a minimum
spacing) so a crash-looping head degrades loudly instead of burning
the host; an *intentional* shutdown calls :meth:`stop` first and never
respawns.
"""

from __future__ import annotations

import logging
import subprocess
import threading
import time
from typing import Any, Callable, Dict, Optional

logger = logging.getLogger(__name__)

__all__ = ["HeadSupervisor"]


class HeadSupervisor:
    """Watch a head subprocess; respawn it in place when it dies.

    ``on_respawn(proc, handshake)`` (optional) lets the owner swap its
    process handle/bookkeeping for the new head.
    """

    #: poll period for the child's liveness (cheap: one waitpid probe)
    _POLL_S = 0.5
    #: minimum spacing between respawns — a head that dies faster than
    #: this is crash-looping, not crashing
    _MIN_SPACING_S = 1.0

    def __init__(self, config: Any, session_dir: str,
                 resources: Optional[Dict[str, float]],
                 proc: subprocess.Popen, gcs_port: int,
                 on_respawn: Optional[Callable[
                     [subprocess.Popen, Dict[str, Any]], None]] = None):
        self._config = config
        self._session_dir = session_dir
        self._resources = resources
        self._proc = proc
        self._gcs_port = int(gcs_port)
        self._on_respawn = on_respawn
        self._stop = threading.Event()
        self._suspended = False
        self._lock = threading.Lock()
        # held across the monitor's whole kill-detect -> spawn -> swap
        # section; suspend() acquires it, so suspension WAITS OUT any
        # respawn already in flight (lock order: _spawn_lock -> _lock)
        self._spawn_lock = threading.Lock()
        self.respawns = 0
        self._last_respawn = 0.0
        self._thread = threading.Thread(
            target=self._run, name="rtpu-head-supervisor", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        """Intentional shutdown: the next head exit is expected.  Takes
        the lock so a respawn in flight finishes swapping (or is
        discarded) before the caller proceeds to terminate the head —
        otherwise shutdown could kill the OLD proc while a freshly
        spawned head survives it, orphaned."""
        with self._lock:
            self._stop.set()

    def attach(self, proc: subprocess.Popen) -> None:
        """Point the supervisor at a head restarted by someone else
        (e.g. an explicit ``Cluster.restart_head``)."""
        with self._lock:
            self._proc = proc

    def suspend(self) -> None:
        """Pause respawning while the owner restarts the head ITSELF
        (``Cluster.restart_head``): without this the supervisor would
        race the explicit restart with its own spawn_head on the same
        GCS port.  Blocks until any respawn already in flight has
        finished (and its swap landed), so the caller proceeds with
        exclusive ownership of the port."""
        with self._spawn_lock:
            with self._lock:
                self._suspended = True

    def resume(self) -> None:
        with self._lock:
            self._suspended = False

    def _run(self) -> None:
        from ray_tpu.core import node as node_mod

        max_respawns = int(getattr(self._config, "gcs_respawn_max", 3))
        while not self._stop.wait(self._POLL_S):
            # the whole detect -> spawn -> swap pass runs under
            # _spawn_lock, so suspend() (an explicit restart_head) and
            # stop() (shutdown) wait out a respawn in flight instead of
            # racing it with a second head on the same port
            with self._spawn_lock:
                if self._respawn_once(node_mod, max_respawns):
                    return

    def _respawn_once(self, node_mod, max_respawns: int) -> bool:
        """One monitor pass under ``_spawn_lock``; True = monitoring is
        over (stopped, or the respawn budget is spent on a dead head)."""
        with self._lock:
            if self._stop.is_set():
                return True
            proc = self._proc
            if self._suspended:
                return False
        if proc.poll() is None:
            return False
        if max_respawns and self.respawns >= max_respawns:
            logger.error(
                "head died (rc=%s) but the respawn budget (%d) is "
                "spent — leaving it down", proc.returncode, max_respawns)
            return True
        since = time.monotonic() - self._last_respawn
        if since < self._MIN_SPACING_S:
            time.sleep(self._MIN_SPACING_S - since)
        logger.warning(
            "head process died (rc=%s); respawning GCS on port %d "
            "(session %s)", proc.returncode, self._gcs_port,
            self._session_dir)
        try:
            new_proc, handshake = node_mod.spawn_head(
                self._config, self._session_dir, self._resources,
                gcs_port=self._gcs_port,
                die_with_parent=node_mod.safe_die_with_parent())
        except Exception:  # noqa: BLE001 — handshake timeout / spawn
            # failure: count it against the budget, retry next poll
            logger.exception("head respawn failed")
            self._last_respawn = time.monotonic()
            self.respawns += 1
            return False
        with self._lock:
            if self._stop.is_set():
                # shutdown raced the respawn: the caller already tore
                # the cluster down — don't orphan this head
                try:
                    new_proc.terminate()
                except Exception:  # noqa: BLE001
                    pass
                return True
            self._proc = new_proc
            # the owner's bookkeeping swap happens under the SAME lock
            # stop() takes, so shutdown always sees (and terminates)
            # the head that actually survives
            if self._on_respawn is not None:
                try:
                    self._on_respawn(new_proc, handshake)
                except Exception:  # noqa: BLE001 — owner bookkeeping
                    logger.exception("on_respawn callback failed")
        self.respawns += 1
        self._last_respawn = time.monotonic()
        try:
            from ray_tpu.core import telemetry as _tm
            _tm.gcs_respawn()
        except Exception:  # noqa: BLE001 — telemetry is best-effort
            pass
        self._ship_dead_head_tail(proc.pid)
        # surviving raylets re-register and drivers reconnect via the
        # PR-11 backoff loops; recovery replays snapshot + WAL
        return False

    def _ship_dead_head_tail(self, dead_pid: int) -> None:
        """Hand the dead head's flight ring to the respawned GCS so the
        incident journal records what the OLD head was doing when it
        died.  Nobody else can: the raylet ships dead workers' rings
        and the GCS reads dead raylets' rings, but when the head itself
        dies the supervisor is the only survivor that knows the pid."""
        import asyncio
        import os

        from ray_tpu.core import flight_recorder as _flight
        from ray_tpu.core import rpc

        async def _ship() -> None:
            for path in _flight.rings_for_pid(self._session_dir,
                                              dead_pid):
                tail = _flight.read_ring(path)
                try:
                    os.unlink(path)  # dead pid: nobody writes it again
                except OSError:
                    pass
                if tail is None or not tail["frames"]:
                    continue
                conn = await rpc.connect(
                    ("127.0.0.1", self._gcs_port), timeout=5.0)
                try:
                    await conn.call("report_flight_tail", {
                        "source": tail["source"],
                        "pid": tail["pid"],
                        "reason": "head process died",
                        "torn": tail["torn"],
                        "frames": tail["frames"][-200:],
                    }, timeout=5.0)
                finally:
                    conn.close()

        try:
            asyncio.run(asyncio.wait_for(_ship(), timeout=15.0))
        except Exception:  # noqa: BLE001 — forensics never blocks
            # the respawn path; a lost tail just means a thinner
            # incident entry
            logger.debug("dead-head flight tail ship failed",
                         exc_info=True)
