"""Continuous sampling profiler with per-task time attribution.

Parity: the reference ships ``ray stack`` (py-spy one-shots) and a
py-spy-backed dashboard profiler button; neither is continuous and
neither attributes samples to *tasks*.  This module is the always-ON
capable half of the profiling plane: a per-process background thread
samples every Python thread's stack via ``sys._current_frames()`` at
``profiler_hz``, tags each sample with the task/actor/job currently
executing on that thread (the worker installs a provider over its
exec-thread tracking table), folds samples into bounded collapsed-stack
counts, and hands deltas to the existing telemetry flush loops, which
ship them to the GCS profile ring over the ``report_profile`` RPC
(drop-don't-block, like metrics/spans).

Design constraints, in priority order:

- **Off is free.**  ``profiler_enabled`` defaults to False; nothing
  starts, no thread exists, and the only hot-path cost anywhere in the
  runtime is the provider dict the worker maintains anyway for task
  cancellation.  The sampler thread is created lazily on the first
  ``configure(enabled=True)`` and parks on an Event while inactive.
- **On is cheap.**  One ``sys._current_frames()`` call per tick (a C
  traversal that takes the GIL briefly), frame->label strings cached by
  code identity, one lock acquisition per tick, plain-int overflow
  counters folded into real telemetry Counters only at drain time.
  At the default 25 Hz this measures <1% on the task microbenchmarks.
- **Bounded.**  The fold table holds at most ``profiler_max_stacks``
  distinct (task, stack) keys; samples that would create a new key
  beyond the cap are counted in ``stacks_dropped`` instead of stored.
  Stacks deeper than ``MAX_DEPTH`` keep their leaf-most frames under a
  ``<truncated>`` root.

Timestamps are wall-clock corrected by the process's GCS clock offset
at drain time (same timebase as spans/task events), so merged profiles
from many hosts describe one window.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import telemetry as _tm

#: frames kept per stack (leaf-most win; deeper stacks get a
#: ``<truncated>`` root so recursion can't explode label length)
MAX_DEPTH = 64

#: provider signature: () -> {thread_ident: (task_name, task_id_hex,
#: actor_hex, job_hex)} for threads currently executing a task
TaskInfoProvider = Callable[[], Dict[int, Tuple]]

_IDLE_KEY = (None, None, None, None)


def _hz_default() -> float:
    try:
        from ray_tpu.core.config import get_config
        return float(getattr(get_config(), "profiler_hz", 25.0))
    except Exception:  # noqa: BLE001 — config unavailable
        return 25.0


def _max_stacks() -> int:
    try:
        from ray_tpu.core.config import get_config
        return int(getattr(get_config(), "profiler_max_stacks", 2000))
    except Exception:  # noqa: BLE001
        return 2000


class SamplingProfiler:
    """One per process; use the module-level singleton helpers."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._thread_ident: Optional[int] = None
        self._stop = False
        self._enabled = False
        self._deadline: Optional[float] = None  # monotonic; None = forever
        self._hz = _hz_default()
        self._provider: Optional[TaskInfoProvider] = None
        # fold state (guarded by _lock)
        self._folds: Dict[Tuple, int] = {}
        self._window_start: Optional[float] = None  # wall clock, local
        self._samples = 0          # samples folded this window
        self._stacks_dropped = 0   # samples lost to the max_stacks cap
        self.samples_total = 0     # lifetime (tests/observability)
        self.stacks_dropped_total = 0
        # frame label cache: (filename, firstlineno, name) -> label
        self._labels: Dict[Tuple, str] = {}
        # parked-thread fast path: ident -> (frame id, code id, lasti,
        # task_key, fold key).  A thread that hasn't moved since the
        # last tick (same top frame, same instruction) reuses its fold
        # key without walking the stack — most threads in most
        # processes sit in a selector/queue wait, so this turns the
        # steady-state tick into a few dict hits
        self._parked: Dict[int, Tuple] = {}
        # thread-name cache (threading.enumerate takes a lock + builds
        # a list; names only change when threads come and go)
        self._names: Dict[int, str] = {}
        self._names_tick = 0

    # -- control -------------------------------------------------------
    def set_task_info_provider(self, provider: TaskInfoProvider) -> None:
        self._provider = provider

    def configure(self, enabled: bool, hz: Optional[float] = None,
                  duration_s: Optional[float] = None) -> None:
        """Process-local switch (driven by config at boot, by the
        ``profiler_control`` RPC at runtime)."""
        with self._lock:
            self._enabled = bool(enabled)
            if hz:
                self._hz = max(1.0, min(200.0, float(hz)))
            if enabled:
                self._deadline = (time.monotonic() + float(duration_s)
                                  if duration_s else None)
        if enabled:
            self._ensure_thread()
            self._wake.set()
        else:
            self._wake.clear()

    def active(self) -> bool:
        if not self._enabled:
            return False
        if self._deadline is not None and time.monotonic() > self._deadline:
            return False
        return True

    def stop(self) -> None:
        """Tear down the sampler thread (tests / process exit)."""
        self._stop = True
        self._enabled = False
        self._wake.set()
        t = self._thread
        if t is not None:
            t.join(timeout=2.0)
        self._thread = None
        self._stop = False
        self._wake.clear()

    def _ensure_thread(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        t = threading.Thread(target=self._run, name="rtpu-profiler",
                             daemon=True)
        self._thread = t
        t.start()

    # -- sampler loop --------------------------------------------------
    def _run(self) -> None:
        self._thread_ident = threading.get_ident()
        while not self._stop:
            if not self.active():
                if self._enabled and self._deadline is not None:
                    # duration elapsed: fall back to dormant until the
                    # next configure() — folded samples stay buffered
                    # for the flush loop to drain
                    self._enabled = False
                    self._wake.clear()
                self._wake.wait(timeout=1.0)
                continue
            t0 = time.perf_counter()
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 — sampling must never die
                pass
            delay = max(0.001, 1.0 / self._hz - (time.perf_counter() - t0))
            time.sleep(delay)

    def _frame_label(self, code) -> str:
        key = (code.co_filename, code.co_firstlineno, code.co_name)
        label = self._labels.get(key)
        if label is None:
            base = os.path.basename(code.co_filename)
            label = f"{code.co_name} ({base}:{code.co_firstlineno})"
            if len(self._labels) < 65536:
                self._labels[key] = label
        return label

    def _sample_once(self) -> None:
        provider = self._provider
        info = provider() if provider is not None else {}
        frames = sys._current_frames()
        now = time.time()
        cap = _max_stacks()
        names = self._names
        self._names_tick -= 1
        if self._names_tick <= 0 or any(i not in names for i in frames):
            names = self._names = {t.ident: t.name
                                   for t in threading.enumerate()}
            self._names_tick = 64
            # reap parked entries of exited threads
            for ident in list(self._parked):
                if ident not in frames:
                    del self._parked[ident]
        parked = self._parked
        with self._lock:
            if self._window_start is None:
                self._window_start = now
            for ident, frame in frames.items():
                if ident == self._thread_ident:
                    continue
                task_key = info.get(ident, _IDLE_KEY)
                # ids, not the objects: caching the frame would pin its
                # locals (and the whole stack) past the thread's use.
                # id reuse with identical lasti+code can misattribute a
                # tick — acceptable at sampling granularity.
                cached = parked.get(ident)
                if cached is not None \
                        and cached[0] == id(frame) \
                        and cached[1] == id(frame.f_code) \
                        and cached[2] == frame.f_lasti \
                        and cached[3] == task_key:
                    key = cached[4]
                else:
                    stack: List[str] = []
                    depth = 0
                    f = frame
                    while f is not None and depth < MAX_DEPTH:
                        stack.append(self._frame_label(f.f_code))
                        f = f.f_back
                        depth += 1
                    if f is not None:
                        stack.append("<truncated>")
                    stack.reverse()  # root first (collapsed order)
                    key = (task_key, names.get(ident, str(ident)),
                           tuple(stack))
                    parked[ident] = (id(frame), id(frame.f_code),
                                     frame.f_lasti, task_key, key)
                cur = self._folds.get(key)
                if cur is None and len(self._folds) >= cap:
                    self._stacks_dropped += 1
                    self.stacks_dropped_total += 1
                    continue
                self._folds[key] = (cur or 0) + 1
                self._samples += 1
                self.samples_total += 1

    # -- drain (called by the telemetry flush loops) -------------------
    def drain(self) -> List[Dict[str, Any]]:
        """Pop the window's folded stacks as wire records, clock-
        corrected onto the GCS timebase.  Also folds the window's
        plain-int sample/drop counters into telemetry Counters (same
        presample pattern as the RPC byte accumulators)."""
        with self._lock:
            if not self._folds and not self._stacks_dropped:
                return []
            folds, self._folds = self._folds, {}
            start = self._window_start or time.time()
            self._window_start = None
            samples, self._samples = self._samples, 0
            dropped, self._stacks_dropped = self._stacks_dropped, 0
        off = _tm.clock_offset()
        end = time.time() + off
        pid = os.getpid()
        out: List[Dict[str, Any]] = []
        for (task_key, thread_name, stack), count in folds.items():
            task, task_id, actor, job = task_key
            out.append({
                "stack": ";".join(stack),
                "count": count,
                "task": task,
                "task_id": task_id,
                "actor": actor,
                "job": job,
                "thread": thread_name,
                "pid": pid,
                "start": start + off,
                "end": end,
            })
        if samples:
            _tm.profiler_samples(samples)
        if dropped:
            _tm.profiler_stack_drops(dropped)
        return out

    def _reset_for_tests(self) -> None:
        self.stop()
        with self._lock:
            self._folds.clear()
            self._window_start = None
            self._samples = 0
            self._stacks_dropped = 0
            self.samples_total = 0
            self.stacks_dropped_total = 0
            self._deadline = None
            self._hz = _hz_default()


# ---------------------------------------------------------------------------
# process singleton
# ---------------------------------------------------------------------------

_profiler: Optional[SamplingProfiler] = None
_singleton_lock = threading.Lock()


def get_profiler() -> SamplingProfiler:
    global _profiler
    if _profiler is None:
        with _singleton_lock:
            if _profiler is None:
                _profiler = SamplingProfiler()
    return _profiler


def set_task_info_provider(provider: TaskInfoProvider) -> None:
    get_profiler().set_task_info_provider(provider)


def configure(enabled: bool, hz: Optional[float] = None,
              duration_s: Optional[float] = None) -> None:
    get_profiler().configure(enabled, hz=hz, duration_s=duration_s)


def active() -> bool:
    p = _profiler
    return p is not None and p.active()


def pending() -> bool:
    """True while a DURATION-BOUNDED window is active or folded samples
    await a flush — the flush loops fast-tick (>=1 Hz) on this so a
    short ``ray-tpu profile --duration 2`` sees its samples arrive.
    Open-ended always-on profiling flushes at the normal metrics period
    (latency doesn't matter there; the fast tick would cost idle CPU
    forever)."""
    p = _profiler
    if p is None:
        return False
    if p.active() and p._deadline is not None:
        return True
    return bool(p._folds) and not p.active()


def drain() -> List[Dict[str, Any]]:
    p = _profiler
    if p is None:
        return []
    return p.drain()


def maybe_start_from_config() -> None:
    """Boot-time hook: start sampling when ``profiler_enabled`` is set
    (config or RAY_TPU_PROFILER_ENABLED env) — the always-on mode."""
    try:
        from ray_tpu.core.config import get_config
        if bool(getattr(get_config(), "profiler_enabled", False)):
            configure(True)
    except Exception:  # noqa: BLE001 — config unavailable: stay off
        pass


# ---------------------------------------------------------------------------
# output formats (consumed by the CLI, dashboard, and tests)
# ---------------------------------------------------------------------------

def merge_records(records: List[Dict[str, Any]],
                  by_task: bool = True) -> List[Dict[str, Any]]:
    """Merge records across workers/processes: same (stack, attribution)
    sums counts.  ``by_task=False`` collapses attribution entirely
    (pure cluster flamegraph)."""
    merged: Dict[Tuple, Dict[str, Any]] = {}
    for rec in records:
        key = (rec.get("stack"),
               (rec.get("task"), rec.get("job")) if by_task else None)
        cur = merged.get(key)
        if cur is None:
            cur = dict(rec)
            cur.pop("pid", None)
            cur.pop("thread", None)
            if not by_task:
                for k in ("task", "task_id", "actor", "job"):
                    cur.pop(k, None)
            merged[key] = cur
        else:
            cur["count"] += rec.get("count", 0)
            cur["start"] = min(cur.get("start", 0), rec.get("start", 0))
            cur["end"] = max(cur.get("end", 0), rec.get("end", 0))
    out = sorted(merged.values(), key=lambda r: -r["count"])
    return out


def to_collapsed(records: List[Dict[str, Any]]) -> str:
    """Brendan-Gregg collapsed-stack text (flamegraph.pl / speedscope
    both ingest it).  Task attribution becomes the root frame so one
    flamegraph splits by task."""
    lines = []
    for rec in records:
        stack = rec.get("stack") or "<unknown>"
        root = rec.get("task")
        if root:
            stack = f"task:{root};{stack}"
        lines.append(f"{stack} {rec.get('count', 0)}")
    return "\n".join(lines) + ("\n" if lines else "")


def to_speedscope(records: List[Dict[str, Any]],
                  name: str = "ray_tpu profile") -> Dict[str, Any]:
    """speedscope 'sampled' profile (https://speedscope.app file
    format): shared frame table + per-sample frame-index lists with
    fold counts as weights."""
    frame_index: Dict[str, int] = {}
    frames: List[Dict[str, str]] = []
    samples: List[List[int]] = []
    weights: List[int] = []
    for rec in records:
        stack = rec.get("stack") or "<unknown>"
        parts = ([f"task:{rec['task']}"] if rec.get("task") else []) \
            + stack.split(";")
        idxs = []
        for part in parts:
            idx = frame_index.get(part)
            if idx is None:
                idx = frame_index[part] = len(frames)
                frames.append({"name": part})
            idxs.append(idx)
        samples.append(idxs)
        weights.append(int(rec.get("count", 0)))
    total = sum(weights)
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "shared": {"frames": frames},
        "profiles": [{
            "type": "sampled",
            "name": name,
            "unit": "none",
            "startValue": 0,
            "endValue": total,
            "samples": samples,
            "weights": weights,
        }],
        "name": name,
        "activeProfileIndex": 0,
        "exporter": "ray_tpu",
    }
