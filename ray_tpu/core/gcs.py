"""Global Control Service: the cluster metadata authority.

Parity: reference ``src/ray/gcs/gcs_server/`` — node membership
(GcsNodeManager), actor directory + lifecycle (GcsActorManager /
GcsActorScheduler), placement groups (GcsPlacementGroupManager, two-phase
prepare/commit), job table, internal KV, function table, health checking
(GcsHealthCheckManager), and the pubsub hub.  Table storage is pluggable
(``core/table_storage.py``): in-memory by default (the reference's default
store client), with a durable file-backed store that lets a restarted head
rehydrate nodes/actors/PGs/jobs/KV — exercised by ``tests/test_chaos.py``
(head SIGKILL mid-workload, same driver finishes).

TPU twist (SURVEY.md §7.2): node registration carries topology metadata —
slice name, chip coordinates, ICI neighbor hints — alongside resources, so
gang scheduling can place co-located bundles on one slice.
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import flight_recorder as _flight
from ray_tpu.core import profiler as _prof
from ray_tpu.core import rpc
from ray_tpu.core import telemetry as _tm
from ray_tpu.core import tracing as _trace
from ray_tpu.core.config import Config
from ray_tpu.core.ids import ActorID, JobID, NodeID, PlacementGroupID
from ray_tpu.autoscaler.fair_queue import (
    NODE_ACTIVE, NODE_DEAD, NODE_DRAINED, NODE_DRAINING, JobQuota,
    validate_transition)
from ray_tpu.util import failpoint as _fp

logger = logging.getLogger(__name__)


@dataclass
class NodeInfo:
    node_id: NodeID
    raylet_address: rpc.Address
    resources_total: Dict[str, float]
    resources_available: Dict[str, float]
    # TPU topology metadata: {"slice": str, "coords": [x,y,z], "worker_index": int}
    topology: Dict[str, Any] = field(default_factory=dict)
    alive: bool = True
    last_heartbeat: float = field(default_factory=time.monotonic)
    # load: number of queued+running lease requests, for hybrid scheduling
    load: int = 0
    # queued resource shapes (autoscaler demand signal)
    pending_demand: List[Dict[str, float]] = field(default_factory=list)
    # per-node reporter payload: cpu/mem + per-worker process stats
    stats: Dict[str, Any] = field(default_factory=dict)
    # worker-process capacity the raylet advertised (-1 = unknown, old
    # raylets); 0 = a dedicated control node that can NEVER host a
    # worker — the actor scheduler must not strand leases there
    max_workers: int = -1
    # lifecycle state (docs/autoscaler.md): ACTIVE | DRAINING | DRAINED
    # | DEAD.  DRAINING/DRAINED nodes keep alive=True (the raylet still
    # serves in-flight work and object pulls) but take no new leases
    state: str = NODE_ACTIVE
    drain_reason: str = ""
    # raylet process id: on a same-host node death the GCS reads the
    # dead raylet's flight ring from the session dir by this pid
    pid: int = 0


#: internal-KV key (default namespace) holding the standing
#: ``autoscaler.sdk.request_resources`` bundles as a JSON list
RESOURCE_REQUEST_KV_KEY = "__autoscaler_resource_request"

#: internal-KV key (default namespace) holding the autoscaler monitor's
#: last decision as JSON ({action, detail, ts}) — surfaced by
#: ``ray-tpu nodes`` so operators see why the fleet last moved
AUTOSCALER_DECISION_KV_KEY = "__autoscaler_last_decision"

#: internal-KV key (namespace ``_internal``) holding the JSON firing
#: alert set — rewritten on every transition so a restarted GCS can
#: re-seed its evaluator (docs/observability.md)
ALERTS_FIRING_KV_KEY = "alerts_firing"

ACTOR_PENDING = "PENDING_CREATION"
ACTOR_ALIVE = "ALIVE"
ACTOR_RESTARTING = "RESTARTING"
ACTOR_DEAD = "DEAD"


@dataclass
class ActorInfo:
    actor_id: ActorID
    state: str = ACTOR_PENDING
    name: Optional[str] = None
    namespace: str = "default"
    detached: bool = False
    address: Optional[rpc.Address] = None  # the actor worker's task server
    node_id: Optional[NodeID] = None
    max_restarts: int = 0
    num_restarts: int = 0
    creation_spec_blob: Optional[bytes] = None  # pickled TaskSpec, for restarts
    resources: Dict[str, float] = field(default_factory=dict)
    owner_job: Optional[JobID] = None
    death_cause: str = ""
    class_name: str = ""
    # gang binding: schedule onto this group's bundle, charged to it
    pg_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    # placement strategy (actor.options(scheduling_strategy=...)):
    # DEFAULT least-loaded, SPREAD fans across nodes by live-actor
    # count, NODE_AFFINITY pins to strategy_node (soft = fall back)
    strategy: str = "DEFAULT"
    strategy_node: Optional[str] = None
    strategy_soft: bool = False
    env_hash: Optional[str] = None
    env_spawn: Optional[Dict[str, Any]] = None
    # owner-reported raylet addresses of nodes already holding the
    # creation args' objects: DEFAULT placement prefers them so the
    # creation task's arg fetch is a local read, not a transfer
    locality: Optional[List[Any]] = None


@dataclass
class PlacementGroupInfo:
    pg_id: PlacementGroupID
    bundles: List[Dict[str, float]]
    strategy: str  # PACK | SPREAD | STRICT_PACK | STRICT_SPREAD
    state: str = "PENDING"  # PENDING | CREATED | REMOVED | INFEASIBLE
    # bundle index -> node id
    bundle_nodes: Dict[int, NodeID] = field(default_factory=dict)
    name: Optional[str] = None
    scheduling: bool = False  # reentrancy guard for _schedule_pg
    retry_at: float = 0.0  # monotonic time of next placement attempt
    retry_backoff: float = 0.5  # grows while unplaceable, capped


class GcsServer:
    """All GCS tables + managers in one asyncio service."""

    def __init__(self, config: Config, host: str = "127.0.0.1",
                 port: int = 0, snapshot_path: Optional[str] = None,
                 session_dir: Optional[str] = None):
        self.config = config
        self.server = rpc.Server(self, host=host, port=port)
        self.pool = rpc.ConnectionPool()
        # structured events (parity: src/ray/util/event.h + the
        # dashboard event module): own emissions + pushes from every
        # process land in one ring buffer behind list_events
        from ray_tpu.util import event as event_mod
        self._event_mod = event_mod
        event_mod.init("GCS", session_dir)
        # crash-surviving flight ring for the head process (the
        # co-located raylet's later init is a no-op — first init wins)
        _flight.init("gcs", session_dir, config)
        self._session_dir = session_dir
        # bounded per-severity event retention rings: a flood of one
        # severity (INFO churn) can no longer evict the sparse ERROR
        # evidence an incident window needs.  Evictions are counted
        # (ray_tpu_events_evicted_total + debug_state).
        from collections import deque as _deque
        self._event_rings: Dict[str, "_deque"] = {}
        self._events_evicted = 0
        # incident journal (docs/observability.md "Incidents and
        # postmortems"): auto-opened on deaths / firing alerts,
        # WAL-persisted like alerts so they survive a head SIGKILL
        from collections import OrderedDict as _inc_od
        self._incidents: "_inc_od[str, Dict[str, Any]]" = _inc_od()
        self._incident_collect_handles: Dict[str, Any] = {}
        # versioned resource-view broadcast (ray_syncer equivalent)
        self._sync_version = 0
        self._sync_dirty: set = set()
        self._sync_task: Optional[asyncio.Task] = None
        # tables
        self.nodes: Dict[NodeID, NodeInfo] = {}
        self.actors: Dict[ActorID, ActorInfo] = {}
        self.named_actors: Dict[Tuple[str, str], ActorID] = {}  # (ns, name)
        self.placement_groups: Dict[PlacementGroupID, PlacementGroupInfo] = {}
        # long-poll waiters for placement_group_ready (kept OUT of
        # PlacementGroupInfo: those objects are pickled by persistence)
        self._pg_waiters: Dict[PlacementGroupID, asyncio.Event] = {}
        self.kv: Dict[str, Dict[str, bytes]] = {}  # namespace -> key -> value
        self.functions: Dict[str, bytes] = {}  # function_id -> pickled blob
        self.job_counter = 0
        self.jobs: Dict[JobID, Dict[str, Any]] = {}
        # per-job scheduling quotas (job key -> JobQuota dict), WAL- and
        # snapshot-covered so fair-queue weights survive a head SIGKILL
        self.quotas: Dict[str, Dict[str, Any]] = {}
        # per-node lease tables: node hex -> {job: {resource: in-flight}}
        # — heartbeat-reported ground truth, WAL'd on change so a GCS
        # restart mid-drain restores in-flight quota accounting
        self.lease_tables: Dict[str, Dict[str, Dict[str, float]]] = {}
        # durable drain-state map (node_id binary -> {state, reason}):
        # the node table itself is rebuilt by live re-registration, but
        # a DRAINING/DRAINED verdict must survive a GCS SIGKILL so the
        # re-registering raylet resumes in the right lifecycle state
        self._node_states: Dict[bytes, Dict[str, Any]] = {}
        # node ids with a drain protocol currently executing (in-memory
        # only: a restarted GCS may re-enter a WAL-restored DRAINING)
        self._drains_inflight: set = set()
        # pubsub: channel -> set of connections
        self.subscribers: Dict[str, set] = {}
        # node connections (raylet registration conns) for death detection
        self._node_conns: Dict[NodeID, rpc.Connection] = {}
        self._health_task: Optional[asyncio.Task] = None
        self._pg_retry_task: Optional[asyncio.Task] = None
        self._actor_creation_locks: Dict[ActorID, asyncio.Lock] = {}
        # coalesced-registration accounting (debug_state surface; the
        # batch-size histogram is the metrics-plane view of the same)
        self._reg_batches = 0
        self._reg_batch_actors = 0
        # source -> (seq, replies) ack cache: a retried batch whose ack
        # was lost re-serves the first pass's replies instead of
        # re-running (and re-counting) the whole batch
        self._reg_batch_acks: Dict[str, Any] = {}
        # node -> unresolved lease_worker_for_actor calls (burst spread)
        self._actor_lease_inflight: Dict[NodeID, int] = {}
        # actor_id -> NodeID charged above (held until actor_started /
        # creation_failed so still-initializing actors keep counting)
        self._actor_lease_charges: Dict[ActorID, NodeID] = {}
        self._task_events: List[Dict[str, Any]] = []  # state API ring buffer
        self._tasks_finished_total = 0  # monotonic (metrics counter)
        # per-source replay high-water marks: report_task_events and
        # report_metrics are retried on lost acks (IDEMPOTENT_METHODS),
        # and their folds accumulate — a replayed flush must be dropped,
        # not re-applied (exactly-once at the fold, like the WAL dedup)
        self._task_event_seq: Dict[str, int] = {}
        self._metric_seq: Dict[str, int] = {}
        # ring-buffer overflow accounting (satellite: silent event loss):
        # job hex -> events evicted unread, plus burst-logging state
        self._task_event_drops: Dict[str, int] = {}
        self._task_event_drops_total = 0
        self._drop_burst_started = 0.0  # 0 = not in an overflow burst
        self._drop_burst_count = 0
        # (name, sorted-tags) -> aggregated metric record
        self._metrics: Dict[Any, Dict[str, Any]] = {}
        # transfer / rpc-retry spans for timeline() (clock-aligned by
        # the reporting process; see telemetry.measure_clock_offset)
        from collections import deque as _dq
        self._spans: "_dq" = _dq(maxlen=getattr(
            config, "telemetry_spans_table_size", 20000))
        # continuous-profiling ring (report_profile producer records,
        # served merged by get_profile) + eviction accounting
        self._profile: "_dq" = _dq(maxlen=getattr(
            config, "profiler_table_size", 50000))
        self._profile_evicted = 0
        # distributed-tracing assembly ring: trace_id -> entry, insertion
        # ordered for eviction.  Entries assemble spans until the root
        # arrives, then TAIL SAMPLING decides retention (errors / sheds /
        # deadline misses / SLO violations / retried traces always kept;
        # fast successes kept at trace_sample_keep_fraction).  A
        # sampled-out entry stays as a tombstone (keep=False, spans
        # cleared) so stragglers from slower processes drop instead of
        # resurrecting the trace; the ring cap evicts oldest-first.
        from collections import OrderedDict as _od
        self._traces: "_od[str, Dict[str, Any]]" = _od()
        self._traces_evicted = 0
        self._traces_retained = 0
        self._traces_sampled_out = 0
        # recently-evicted trace ids: stragglers flushing after their
        # trace (or its tombstone) left the ring must DROP, not
        # resurrect a rootless phantom entry that occupies a slot and
        # can never complete
        self._trace_evicted_ids: "_dq[str]" = _dq()
        self._trace_evicted_set: set = set()
        #: spans kept per trace before truncation (a runaway decode
        #: loop must not let one trace eat the ring's memory)
        self._trace_span_cap = 512
        #: live cluster profiling window ({enabled, hz, deadline}) for
        #: raylets that register mid-window
        self._profiler_state: Optional[Dict[str, Any]] = None
        self._metrics_task: Optional[asyncio.Task] = None
        # durable tables behind the pluggable TableStorage interface
        # (reference: GcsTableStorage over Redis/in-memory store clients):
        # kv, functions, jobs, the FULL actor table, and placement groups
        # survive a GCS/head restart; nodes re-register live
        from ray_tpu.core.table_storage import (InMemoryTableStorage,
                                                make_table_storage)
        self.table_storage = make_table_storage(
            getattr(config, "gcs_table_storage", ""), snapshot_path)
        self._persist_handle: Optional[asyncio.TimerHandle] = None
        #: actors restored ALIVE from a snapshot pending a liveness probe
        self._actors_to_revalidate: List[ActorInfo] = []
        #: actors restored mid-scheduling (PENDING/RESTARTING)
        self._actors_to_reschedule: List[ActorInfo] = []
        # write-ahead log in front of the snapshot (docs/ha.md): table-
        # mutating handlers append a typed record and hold the reply
        # until it is durable, so an acked mutation survives a SIGKILL
        # inside the snapshot debounce window.  Ephemeral (memory)
        # clusters run without one.
        self.wal = None
        self._wal_degraded = False
        #: last FAILED snapshot write (cooldown clock: a failing
        #: backend must not retry size-triggered compaction
        #: per-mutation)
        self._persist_failed_ts = 0.0
        if getattr(config, "gcs_wal_enabled", True) \
                and not isinstance(self.table_storage,
                                   InMemoryTableStorage):
            from ray_tpu.core.wal import WriteAheadLog
            wal_path = os.path.join(session_dir, "gcs_wal.log") \
                if session_dir else (snapshot_path or "") + ".wal"
            if wal_path and wal_path != ".wal":
                self.wal = WriteAheadLog(
                    wal_path,
                    sync=getattr(config, "gcs_wal_sync", "fsync"))
        #: restart-recovery / reconvergence accounting (served by
        #: handle_recovery_state; duration finalized after the restored
        #: actors were revalidated)
        self._recovery: Dict[str, Any] = {
            "restored": False, "wal_records_replayed": 0,
            "wal_torn_tail_bytes": 0, "actors_recovered": 0,
            "actors_revalidated": 0, "actors_rescheduled": 0,
            "nodes_expected": 0, "complete": True, "duration_s": 0.0,
        }
        self._recovery_t0 = time.monotonic()
        #: nodes known to the previous incarnation (WAL node records):
        #: the reconvergence denominator — raylets re-register live,
        #: this just tells recovery_state how many to expect
        self._wal_nodes: Dict[bytes, Dict[str, Any]] = {}
        self._restore_snapshot()
        # metrics history + alert evaluator (core/metrics_history.py):
        # constructed AFTER the restore so a firing set persisted by
        # the previous incarnation (internal KV) seeds the evaluator —
        # a firing alert survives a head SIGKILL as re-firing-or-
        # resolved, never silently lost
        from ray_tpu.core.metrics_history import MetricsHistory
        restored_firing = None
        try:
            raw = self.kv.get("_internal", {}).get(ALERTS_FIRING_KV_KEY)
            if raw:
                import json as _json
                restored_firing = _json.loads(raw.decode())
        except Exception:  # noqa: BLE001 — corrupt state: start clean
            logger.exception("restored alert state unreadable; ignored")
        self._history = MetricsHistory(
            interval_s=getattr(config, "metrics_history_interval_s", 2.0),
            window_s=getattr(config, "metrics_history_window_s", 300.0),
            slo_latency_s=getattr(config, "serve_slo_latency_s", 0.0),
            slo_error_budget=getattr(config, "serve_slo_error_budget",
                                     0.01),
            restored_firing=restored_firing)
        self._history_evicted_reported = 0
        self._history_task: Optional[asyncio.Task] = None

    def _restore_snapshot(self) -> None:
        """Recovery: load the snapshot, replay the WAL on top (typed
        set-style records — replaying records the snapshot already
        covers converges, see core/wal.py), then classify the restored
        actors for revalidation/rescheduling."""
        snap = self.table_storage.load()
        if snap is not None:
            self.kv = snap.get("kv", {})
            self.functions = snap.get("functions", {})
            self.jobs = snap.get("jobs", {})
            self.job_counter = snap.get("job_counter", 0)
            self.quotas = snap.get("quotas", {})
            self.lease_tables = snap.get("lease_tables", {})
            self._node_states = snap.get("node_states", {})
            for inc in snap.get("incidents", []):
                self._incidents[inc["id"]] = inc
            # full actor runtime state (not just detached): a
            # reconnecting driver's handles must keep resolving after a
            # head restart
            for info in snap.get("actors",
                                 snap.get("detached_actors", [])):
                self.actors[info.actor_id] = info
            for pg_id, info in snap.get("placement_groups", {}).items():
                self.placement_groups[pg_id] = info
        n_wal = 0
        if self.wal is not None:
            try:
                for _seq, rtype, data in self.wal.recover():
                    try:
                        self._wal_apply(rtype, data)
                        n_wal += 1
                    except Exception:  # noqa: BLE001 — skip a bad record
                        logger.exception("WAL record %r failed to apply",
                                         rtype)
                self._recovery["wal_torn_tail_bytes"] = \
                    self.wal.torn_tail_bytes
            except Exception:  # noqa: BLE001 — recovery must not crash
                logger.exception("WAL recovery failed; snapshot only")
                self._wal_degrade("recovery failed")
            _tm.gcs_wal_replayed(n_wal)
        if snap is None and n_wal == 0:
            return  # cold start
        # classification AFTER replay, so WAL-recovered actors adopt
        # the same restored-ALIVE liveness probes / reschedule paths as
        # snapshot-recovered ones
        self.named_actors = {}
        for info in self.actors.values():
            if info.name and info.state != ACTOR_DEAD:
                self.named_actors[(info.namespace or "default",
                                   info.name)] = info.actor_id
            if info.state == ACTOR_ALIVE:
                # the worker may have died with the head (or survived on
                # a side node) — probed once the server is up
                self._actors_to_revalidate.append(info)
            elif info.state in (ACTOR_PENDING, ACTOR_RESTARTING):
                # scheduling was in flight when the head died; nothing
                # else will resume it (no node-lost event fires for an
                # actor with no node) — reschedule after startup
                self._actors_to_reschedule.append(info)
        # placement groups: bundles stay committed on surviving raylets;
        # restoring the table keeps lookup/removal working after restart
        # (parity: reference GcsTableStorage persists the PG table too)
        for info in self.placement_groups.values():
            info.scheduling = False
            # retry_at is a monotonic timestamp from the previous boot —
            # meaningless now; reset so pending groups reschedule promptly
            info.retry_at = 0.0
            info.retry_backoff = 0.5
        self._recovery.update(
            restored=True, wal_records_replayed=n_wal,
            actors_recovered=len(self.actors),
            actors_revalidated=len(self._actors_to_revalidate),
            actors_rescheduled=len(self._actors_to_reschedule),
            nodes_expected=len(self._wal_nodes),
            complete=not (self._actors_to_revalidate
                          or self._actors_to_reschedule),
            duration_s=round(time.monotonic() - self._recovery_t0, 3))
        logger.info(
            "GCS restored from %s (+%d WAL records): %d kv namespaces, "
            "%d functions, %d jobs, %d actors",
            self.table_storage.describe(), n_wal, len(self.kv),
            len(self.functions), len(self.jobs), len(self.actors))

    # -- write-ahead log (core/wal.py; docs/ha.md) ---------------------
    def _wal_append(self, rtype: str, data: Any) -> None:
        """Enqueue one typed mutation record.  WAL trouble degrades to
        snapshot-only persistence — the mutation itself never fails."""
        if self.wal is None:
            return
        try:
            self.wal.append(rtype, data)
            _tm.gcs_wal_append()
            if _flight.enabled():
                # WAL position in the ring: a postmortem of a dead GCS
                # shows exactly how far durability had advanced
                _flight.record("wal_append",
                               f"{rtype} n={self.wal.appends} "
                               f"bytes={self.wal.size_bytes}")
        except Exception as e:  # noqa: BLE001 — durability degrades,
            self._wal_degrade(e)  # availability stays
        else:
            if self.wal.size_bytes > int(getattr(
                    self.config, "gcs_wal_compact_bytes", 8 << 20)) \
                    and time.monotonic() - self._persist_failed_ts \
                    >= 1.0:
                # the cooldown matters when store() keeps FAILING (the
                # log can't truncate, so the size check stays true):
                # without it every mutation would retry a synchronous
                # full-table snapshot inline in its handler, collapsing
                # control-plane latency exactly while the storage
                # backend is degraded.  Healthy compactions are
                # untouched — success resets the clock.
                self._compact_now()

    async def _wal_flush(self) -> None:
        """Await durability of every record appended so far — called by
        mutating handlers right before their reply, sharing one
        group-commit fsync per event-loop window."""
        if self.wal is None:
            return
        fsyncs = self.wal.fsyncs
        try:
            await self.wal.flush()
        except Exception as e:  # noqa: BLE001
            self._wal_degrade(e)
        else:
            _tm.gcs_wal_fsync(self.wal.fsyncs - fsyncs)

    def _wal_degrade(self, reason: Any) -> None:
        """Disable the WAL after an append/flush failure: persistence
        falls back to the tight snapshot debounce (0.2 s), counted and
        surfaced so operators see the durability downgrade."""
        if self.wal is None:
            return
        logger.error("GCS WAL degraded to snapshot-only persistence: %s",
                     reason)
        _tm.gcs_wal_append_failure()
        self._emit_event("ERROR", "GCS_WAL_DEGRADED",
                         f"WAL disabled, snapshot-only persistence: "
                         f"{reason}")
        try:
            self.wal.close()
        finally:
            self.wal = None
            self._wal_degraded = True

    def _wal_actor(self, info: ActorInfo) -> None:
        """Full-state actor record (idempotent on replay: last write
        wins, the name index is rederived from state)."""
        self._wal_append("actor", info)

    def _wal_pg(self, pg: PlacementGroupInfo) -> None:
        self._wal_append("pg", pg)

    def _wal_apply(self, rtype: str, data: Any) -> None:
        """Re-apply one replayed record to the in-memory tables.  Every
        record is a full-value set (never a delta), so records the
        snapshot already covers replay to the same state."""
        if rtype == "kv_put":
            ns, key, value, overwrite = data
            d = self.kv.setdefault(ns, {})
            if overwrite or key not in d:
                d[key] = value
        elif rtype == "kv_del":
            ns, key = data
            self.kv.get(ns, {}).pop(key, None)
        elif rtype == "function":
            fid, blob = data
            self.functions[fid] = blob
        elif rtype == "job":
            jid, record, counter = data
            self.jobs[JobID(jid)] = record
            self.job_counter = max(self.job_counter, counter)
        elif rtype == "actor":
            self.actors[data.actor_id] = data
        elif rtype == "pg":
            if data.state == "REMOVED":
                self.placement_groups.pop(data.pg_id, None)
            else:
                self.placement_groups[data.pg_id] = data
        elif rtype == "node":
            self._wal_nodes[data["node_id"]] = data
        elif rtype == "node_dead":
            self._wal_nodes.pop(data["node_id"], None)
            self._node_states.pop(data["node_id"], None)
            # a dead node's lease accounting dies with it — without
            # this, replay resurrects quota charges for capacity that
            # no longer exists (mirror of _mark_node_dead)
            self.lease_tables.pop(data["node_id"].hex(), None)
        elif rtype == "node_state":
            nid, state, reason = data
            if state in (NODE_DRAINING, NODE_DRAINED):
                self._node_states[nid] = {"state": state,
                                          "reason": reason}
            else:  # back to ACTIVE (drain aborted) or released
                self._node_states.pop(nid, None)
        elif rtype == "quota":
            job, quota = data
            if quota is None:
                self.quotas.pop(job, None)
            else:
                self.quotas[job] = quota
        elif rtype == "lease_table":
            node_hex, usage = data
            if usage:
                self.lease_tables[node_hex] = usage
            else:
                self.lease_tables.pop(node_hex, None)
        elif rtype == "incident":
            # full-value set: open and collect both re-WAL the whole
            # incident dict, so replay converges on the latest state
            self._incidents[data["id"]] = data
            self._incidents.move_to_end(data["id"])
            cap = max(4, int(getattr(self.config,
                                     "incident_table_size", 200)))
            while len(self._incidents) > cap:
                self._incidents.popitem(last=False)
        else:
            logger.warning("unknown WAL record type %r skipped", rtype)

    def _persistence_health(self) -> Dict[str, Any]:
        """Backend + WAL health for debug_state / ``ray-tpu status``."""
        ts = self.table_storage
        out: Dict[str, Any] = {
            "backend": ts.describe(),
            "persist_failures": ts.persist_failures,
            "last_persist_age_s": round(
                time.time() - ts.last_persist_ts, 3)
            if ts.last_persist_ts else None,
            "wal_degraded": self._wal_degraded,
        }
        if self.wal is not None:
            out["wal"] = {
                "path": self.wal.path,
                "size_bytes": self.wal.size_bytes,
                "appends": self.wal.appends,
                "fsyncs": self.wal.fsyncs,
                "truncations": self.wal.truncations,
                "sync": self.wal.sync,
            }
        return out

    async def handle_recovery_state(self, conn, data):
        """Restart-recovery / reconvergence snapshot: what was restored
        (snapshot + WAL replay), how many restored actors are still
        being revalidated/rescheduled, and how many of the previous
        incarnation's nodes have re-registered."""
        out = dict(self._recovery)
        out["nodes_reregistered"] = sum(
            1 for nid in self._wal_nodes
            if NodeID(nid) in self.nodes and self.nodes[NodeID(nid)].alive)
        out["actors_alive"] = sum(1 for a in self.actors.values()
                                  if a.state == ACTOR_ALIVE)
        return out

    def _schedule_persist(self) -> None:
        """Debounced snapshot write (coalesces mutation bursts).  With
        a healthy WAL the snapshot is only the compaction base, so the
        debounce can stretch (``gcs_snapshot_debounce_s``); without one
        it is the sole durability tier and stays tight."""
        if self._persist_handle is not None:
            return
        delay = float(getattr(self.config,
                              "gcs_snapshot_debounce_s", 2.0)) \
            if self.wal is not None else 0.2
        loop = asyncio.get_running_loop()
        self._persist_handle = loop.call_later(delay, self._persist_now)

    def _compact_now(self) -> None:
        """WAL grew past gcs_wal_compact_bytes: fold it into the
        snapshot immediately instead of waiting out the debounce."""
        if self._persist_handle is not None:
            self._persist_handle.cancel()
            self._persist_handle = None
        self._persist_now()

    def _persist_now(self) -> None:
        self._persist_handle = None
        actors = [a for a in self.actors.values()
                  if a.state != ACTOR_DEAD]
        pgs = {pid: info for pid, info in self.placement_groups.items()
               if info.state != "REMOVED"}
        ok = self.table_storage.store({
            "kv": self.kv, "functions": self.functions,
            "jobs": self.jobs, "job_counter": self.job_counter,
            "actors": actors,
            "placement_groups": pgs,
            "quotas": self.quotas,
            "lease_tables": self.lease_tables,
            "node_states": self._node_states,
            "incidents": list(self._incidents.values())})
        self._persist_failed_ts = 0.0 if ok else time.monotonic()
        # no awaits since the table reads above: the snapshot is a
        # consistent cut covering every WAL record appended so far, so
        # the log truncates (compaction) — but only against a snapshot
        # that actually landed
        if ok and self.wal is not None:
            try:
                self.wal.truncate()
                # the snapshot does NOT carry node membership (raylets
                # re-register live), so re-seed the reconvergence
                # denominator the truncate just erased: one record per
                # live node.  Direct appends — no size re-check, no
                # flush (membership is advisory; the next handler
                # flush covers it).
                for node in self.nodes.values():
                    if node.alive:
                        self.wal.append("node", {
                            "node_id": node.node_id.binary(),
                            "address": list(node.raylet_address),
                            "resources": node.resources_total,
                            "topology": node.topology})
            except Exception as e:  # noqa: BLE001 — truncate/append
                self._wal_degrade(e)  # trouble degrades, never raises

    async def _revalidate_restored_actors(self) -> None:
        """Probe actors restored ALIVE from the snapshot: a worker that
        survived on a side node keeps serving (and will re-announce via
        actor_started when its own GCS reconnect lands); one that died
        with the head goes through the normal restart-or-dead path."""
        pending, self._actors_to_revalidate = \
            self._actors_to_revalidate, []
        for info in pending:
            alive = False
            if info.address:
                try:
                    conn = await rpc.connect(tuple(info.address),
                                             timeout=3.0)
                    try:
                        await conn.call("ping", {}, timeout=3.0)
                        alive = True
                    finally:
                        conn.close()
                except Exception:  # noqa: BLE001 — unreachable = dead
                    alive = False
            if not alive and info.state == ACTOR_ALIVE:
                self._on_actor_worker_lost(
                    info.actor_id, "worker lost in head restart")

    async def start(self) -> rpc.Address:
        address = await self.server.start()
        if self._actors_to_revalidate or self._actors_to_reschedule:
            async def _delayed_revalidate():
                # give surviving side raylets/workers a beat to re-register
                # before probing, so live actors aren't misjudged
                await asyncio.sleep(2.0)
                resched, self._actors_to_reschedule = \
                    self._actors_to_reschedule, []
                for info in resched:
                    if info.state == ACTOR_ALIVE:
                        # the actor's worker survived the restart and
                        # re-announced (actor_started) during the grace:
                        # rescheduling now would mint a SECOND worker
                        continue
                    t = asyncio.get_running_loop().create_task(
                        self._schedule_actor(info))
                    t.add_done_callback(lambda t: t.exception())
                await self._revalidate_restored_actors()
                self._recovery["complete"] = True
                self._recovery["duration_s"] = round(
                    time.monotonic() - self._recovery_t0, 3)
                _tm.gcs_recovery_duration(self._recovery["duration_s"])
            t = asyncio.get_running_loop().create_task(_delayed_revalidate())
            t.add_done_callback(lambda t: t.exception())
        self._health_task = asyncio.get_running_loop().create_task(
            self._health_check_loop()
        )
        self._pg_retry_task = asyncio.get_running_loop().create_task(
            self._pg_retry_loop()
        )
        self._sync_task = asyncio.get_running_loop().create_task(
            self._resource_sync_loop()
        )
        self._metrics_task = asyncio.get_running_loop().create_task(
            self._metrics_flush_loop()
        )
        if getattr(self.config, "metrics_history_enabled", True):
            self._history_task = asyncio.get_running_loop().create_task(
                self._history_loop()
            )
        # always-on profiling mode: the GCS process samples itself too
        _prof.maybe_start_from_config()
        if getattr(self.config, "event_stats", True):
            from ray_tpu.util.event_stats import HandlerStats, LoopMonitor
            self.server.handler_stats = HandlerStats()
            self._loop_monitor = LoopMonitor("gcs",
                                             self.server.handler_stats)
            self._loop_monitor.start()
        logger.info("GCS listening on %s", address)
        return address

    async def handle_debug_state(self, conn, data):
        """Event-loop lag + per-handler timing snapshot (parity: the
        reference's event_stats / debug_state.txt dump), plus telemetry
        plane health (ring-buffer drops, table sizes)."""
        mon = getattr(self, "_loop_monitor", None)
        out = mon.snapshot() if mon is not None else {}
        out["task_event_drops_total"] = self._task_event_drops_total
        out["task_event_drops"] = dict(self._task_event_drops)
        out["metrics_series"] = len(self._metrics)
        out["spans_buffered"] = len(self._spans)
        out["profile_records"] = len(self._profile)
        out["profile_records_evicted"] = self._profile_evicted
        out["traces"] = len(self._traces)
        out["traces_retained"] = self._traces_retained
        out["traces_sampled_out"] = self._traces_sampled_out
        out["traces_evicted"] = self._traces_evicted
        out["registration_batches"] = self._reg_batches
        out["registration_batch_actors"] = self._reg_batch_actors
        out["persistence"] = self._persistence_health()
        out["recovery"] = dict(self._recovery)
        out["history"] = self._history.stats()
        out["events_evicted"] = self._events_evicted
        out["event_rings"] = {sev: len(ring) for sev, ring
                              in self._event_rings.items()}
        out["incidents"] = len(self._incidents)
        out["incidents_open"] = sum(1 for i in self._incidents.values()
                                    if i["state"] == "open")
        fstats = _flight.stats()
        if fstats is not None:
            out["flight_recorder"] = fstats
        return out

    # -- versioned resource broadcast (parity: ray_syncer.h:27-60 —
    # batched, versioned snapshots of per-node resource views instead of
    # every raylet polling the full node table each heartbeat) ---------
    def _mark_sync_dirty(self, node_id: NodeID) -> None:
        self._sync_dirty.add(node_id)

    def _node_view_entry(self, info: "NodeInfo") -> Dict[str, Any]:
        return {
            "node_id": info.node_id.binary(),
            "address": info.raylet_address,
            "alive": info.alive,
            "resources_total": info.resources_total,
            "resources_available": info.resources_available,
            "topology": info.topology,
            "load": info.load,
            "state": info.state,
        }

    async def _metrics_flush_loop(self) -> None:
        """GCS-local producer half: this process's registry deltas and
        spans fold straight into the cluster tables (no RPC hop).  In
        the head process a co-located raylet also flushes the shared
        registry over RPC — each delta still lands exactly once, since
        ``flush_all`` clears what it returns."""
        from ray_tpu.util import metrics as metrics_mod

        period = max(0.25, getattr(self.config,
                                   "metrics_report_period_s", 5.0))
        while True:
            await asyncio.sleep(min(period, 1.0) if _prof.pending()
                                else period)
            # profile records flush even with metrics disabled (the
            # profiler is armed explicitly; same rule as the worker/
            # raylet loops; trace spans likewise flush independently)
            if not _tm.enabled() and not _prof.pending() \
                    and not _trace.pending():
                continue
            try:
                if self._history_task is None:
                    # history plane off: stale-gauge pruning still has
                    # to happen somewhere periodic (it used to live in
                    # the read handler)
                    self._sweep_stale_metrics()
                if _tm.enabled():
                    _tm.set_gauge(
                        "ray_tpu_gcs_subscriber_channels",
                        "live pubsub channels on the GCS hub",
                        len(self.subscribers))
                    if self.wal is not None:
                        _tm.gcs_wal_size(self.wal.size_bytes)
                    fstats = _flight.stats()
                    if fstats is not None:
                        _tm.flight_frames(fstats["frames_recorded"])
                    _tm.incidents_open(
                        sum(1 for i in self._incidents.values()
                            if i["state"] == "open"))
                    _tm.presample()
                    self._ingest_metrics(metrics_mod.flush_all())
                    spans = _tm.drain_spans("gcs")  # offset 0 by defn
                    if spans:
                        self._spans.extend(spans)
                for tspan in _trace.drain("gcs"):
                    self._ingest_trace_span(tspan)
                profile = _prof.drain()
                if profile:
                    for rec in profile:
                        rec["node"] = "gcs"
                        rec["source"] = "gcs"
                    await self.handle_report_profile(
                        None, {"records": profile})
            except Exception:
                logger.exception("GCS-local metrics flush failed")

    async def _resource_sync_loop(self) -> None:
        period = getattr(self.config, "resource_broadcast_period_s", 0.1)
        while True:
            await asyncio.sleep(period)
            if not self._sync_dirty:
                continue
            dirty, self._sync_dirty = self._sync_dirty, set()
            self._sync_version += 1
            entries = [self._node_view_entry(self.nodes[nid])
                       for nid in dirty if nid in self.nodes]
            self.publish("resource_view", {
                "version": self._sync_version,
                "nodes": entries,
            })

    async def stop(self) -> None:
        if getattr(self, "_sync_task", None):
            self._sync_task.cancel()
        if getattr(self, "_metrics_task", None):
            self._metrics_task.cancel()
        if getattr(self, "_history_task", None):
            self._history_task.cancel()
        if getattr(self, "_loop_monitor", None) is not None:
            self._loop_monitor.stop()
        if self._health_task:
            self._health_task.cancel()
        if self._pg_retry_task:
            self._pg_retry_task.cancel()
        for handle in self._incident_collect_handles.values():
            handle.cancel()
        self._incident_collect_handles.clear()
        await self.server.stop()
        self.pool.close_all()
        if self._persist_handle is not None:
            self._persist_handle.cancel()
            self._persist_handle = None
        if self.wal is not None or self.table_storage.last_persist_ts:
            # final snapshot so a graceful stop leaves a compact state
            # (the WAL covers a SIGKILL; this covers tidy shutdowns)
            try:
                self._persist_now()
            except Exception:  # noqa: BLE001 — shutdown best-effort
                logger.exception("final GCS snapshot failed")
        if self.wal is not None:
            self.wal.close()
        # graceful exit unlinks the ring: a surviving ring for a dead
        # pid then unambiguously means crash (see flight_recorder.py)
        _flight.close(unlink=True)

    # ------------------------------------------------------------------
    # pubsub hub
    # ------------------------------------------------------------------
    def publish(self, channel: str, message: Any) -> None:
        delivered = 0
        for conn in list(self.subscribers.get(channel, ())):
            if conn.closed:
                self.subscribers[channel].discard(conn)
            else:
                conn.push(channel, message)
                delivered += 1
        _tm.gcs_published(channel, delivered)

    async def handle_subscribe(self, conn, data):
        channel = data["channel"]
        self.subscribers.setdefault(channel, set()).add(conn)
        return True

    async def handle_unsubscribe(self, conn, data):
        subs = self.subscribers.get(data["channel"])
        if subs is not None:
            subs.discard(conn)
            if not subs:  # don't accrete empty per-actor channel keys
                del self.subscribers[data["channel"]]
        return True

    async def handle_publish(self, conn, data):
        self.publish(data["channel"], data["message"])
        return True

    def on_disconnection(self, conn) -> None:
        for channel in list(self.subscribers):
            subs = self.subscribers[channel]
            subs.discard(conn)
            if not subs:
                # drop emptied keys: auto-subscribed per-actor channels
                # would otherwise accrete one entry per actor per
                # departed driver
                del self.subscribers[channel]
        node_id = conn.context.get("node_id")
        if node_id is not None and node_id in self.nodes:
            self._mark_node_dead(node_id, "raylet connection lost")
        actor_id = conn.context.get("actor_id")
        if actor_id is not None:
            self._on_actor_worker_lost(actor_id, "actor worker connection lost")

    # ------------------------------------------------------------------
    # node membership + health (GcsNodeManager / GcsHealthCheckManager)
    # ------------------------------------------------------------------
    async def handle_register_node(self, conn, data):
        # failpoint: registration rejected/stalled — the raylet's boot
        # (or its reconnect loop) must retry, keyed on its stable node_id
        await _fp.afailpoint("gcs.register_node.fail")
        peer_proto = data.get("protocol_version", rpc.PROTOCOL_VERSION)
        if peer_proto != rpc.PROTOCOL_VERSION:
            raise rpc.RpcError(
                f"wire protocol mismatch: node speaks v{peer_proto}, "
                f"GCS speaks v{rpc.PROTOCOL_VERSION} — upgrade the "
                f"older side")
        node_id = NodeID(data["node_id"])
        info = NodeInfo(
            node_id=node_id,
            raylet_address=tuple(data["raylet_address"]),
            resources_total=dict(data["resources"]),
            resources_available=dict(data["resources"]),
            topology=data.get("topology", {}),
            max_workers=int(data.get("max_workers", -1)),
            pid=int(data.get("pid", 0)),
        )
        # a node re-registering after a GCS restart resumes the
        # lifecycle state the WAL/snapshot recorded for it — a drain
        # verdict is durable, registration must not silently reactivate
        durable = self._node_states.get(node_id.binary())
        if durable:
            info.state = durable.get("state", NODE_ACTIVE)
            info.drain_reason = durable.get("reason", "")
        self.nodes[node_id] = info
        self._node_conns[node_id] = conn
        conn.context["node_id"] = node_id
        # node record: raylets re-register LIVE after a restart (the
        # node table itself is never restored), but the WAL-carried
        # membership gives the recovery protocol its reconvergence
        # denominator (recovery_state.nodes_expected)
        self._wal_append("node", {"node_id": node_id.binary(),
                                  "address": list(info.raylet_address),
                                  "resources": info.resources_total,
                                  "topology": info.topology})
        self.publish("nodes", {"event": "alive", "node_id": node_id.binary(),
                               "address": info.raylet_address})
        self._mark_sync_dirty(node_id)
        logger.info("node %s registered: %s", node_id.hex()[:12], info.resources_total)
        # hand a raylet registering MID-profiling-window the remaining
        # slice so its node doesn't show up as a gap in the profile
        prof = None
        state = self._profiler_state
        if state and state.get("enabled"):
            deadline = state.get("deadline")
            remaining = None if deadline is None \
                else deadline - time.monotonic()
            if remaining is None or remaining > 0:
                prof = {"enabled": True, "hz": state.get("hz"),
                        "duration_s": remaining}
            else:
                self._profiler_state = None
        return {"config": self.config.to_json(), "profiler": prof,
                "state": info.state, "quotas": dict(self.quotas)}

    async def handle_health_report(self, conn, data):
        # failpoint: a stalled/failed heartbeat ack — raylets must ride
        # it out (miss counter + reconnect), never wedge or false-exit
        await _fp.afailpoint("gcs.heartbeat.delay")
        node_id = NodeID(data["node_id"])
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return {"acked": False}  # tells a zombie raylet to exit
        info.last_heartbeat = time.monotonic()
        info.resources_available = dict(data["resources_available"])
        info.load = data.get("load", 0)
        info.pending_demand = list(data.get("pending_demand", []))
        if data.get("node_stats"):
            info.stats = data["node_stats"]
        if "lease_usage" in data:
            # per-job in-flight resource ledger (the raylet's fair-queue
            # ground truth).  WAL'd only on change: the heartbeat path
            # is hot, and replaying the last-known table is enough for a
            # restarted GCS to restore quota accounting exactly-once —
            # the next beat re-reports and converges any tail loss.
            usage = {j: u for j, u in
                     (data.get("lease_usage") or {}).items() if u}
            node_hex = node_id.hex()
            if usage != self.lease_tables.get(node_hex, {}):
                if usage:
                    self.lease_tables[node_hex] = usage
                else:
                    self.lease_tables.pop(node_hex, None)
                self._wal_append("lease_table", (node_hex, usage))
                self._schedule_persist()
        self._mark_sync_dirty(node_id)
        # piggyback the quota table + lifecycle verdict on the ack: a
        # raylet that missed the drain RPC (or re-registered against a
        # restarted GCS) self-corrects within one beat
        return {"acked": True, "state": info.state,
                "quotas": dict(self.quotas)}

    async def handle_get_cluster_load(self, conn, data):
        """Aggregate view for the autoscaler (parity: the monitor reading
        resource load + demand from GCS)."""
        pending_pgs = []
        for pg in self.placement_groups.values():
            if pg.state in ("PENDING", "INFEASIBLE"):
                pending_pgs.append({"strategy": pg.strategy,
                                    "bundles": pg.bundles})
        return {
            "nodes": [
                {"node_id": n.node_id.hex(), "alive": n.alive,
                 "state": n.state,
                 "resources_total": n.resources_total,
                 "resources_available": n.resources_available,
                 "load": n.load}
                for n in self.nodes.values()
            ],
            "pending_demand": [d for n in self.nodes.values() if n.alive
                               for d in n.pending_demand],
            "resource_requests": self._requested_resources(),
            "pending_placement_groups": pending_pgs,
        }

    def _requested_resources(self):
        """Standing ``autoscaler.sdk.request_resources`` bundles (stored
        in internal KV by the SDK; reference autoscaler/sdk/sdk.py:206).
        Reported separately from queued-work demand: the autoscaler
        packs these against TOTAL capacity (a min-cluster-size request,
        not a reservation) and they must not pin unrelated idle
        nodes."""
        import json

        raw = self.kv.get("", {}).get(RESOURCE_REQUEST_KV_KEY)
        if not raw:
            return []
        try:
            return [b for b in json.loads(raw) if isinstance(b, dict)]
        except (ValueError, TypeError):
            return []

    async def handle_get_nodes(self, conn, data):
        return [
            {
                "node_id": n.node_id.binary(),
                "address": n.raylet_address,
                "alive": n.alive,
                "state": n.state,
                "drain_reason": n.drain_reason,
                "resources_total": n.resources_total,
                "resources_available": n.resources_available,
                "topology": n.topology,
                "load": n.load,
                "stats": n.stats,
            }
            for n in self.nodes.values()
        ]

    def _set_node_state(self, info: NodeInfo, new_state: str,
                        reason: str = "") -> None:
        """One lifecycle transition: validated against the matrix,
        WAL'd (durable across a GCS SIGKILL), broadcast on both the
        nodes channel and the versioned resource view."""
        validate_transition(info.state, new_state)
        info.state = new_state
        info.drain_reason = reason
        nid = info.node_id.binary()
        if new_state in (NODE_DRAINING, NODE_DRAINED):
            self._node_states[nid] = {"state": new_state,
                                      "reason": reason}
        else:
            self._node_states.pop(nid, None)
        self._wal_append("node_state", (nid, new_state, reason))
        self._schedule_persist()
        self._mark_sync_dirty(info.node_id)
        _tm.node_drain_transition(new_state)
        self._emit_event(
            "INFO", "NODE_STATE",
            f"node {info.node_id.hex()[:12]} -> {new_state}"
            + (f": {reason}" if reason else ""),
            node_id=info.node_id.hex(), state=new_state)
        self.publish("nodes", {"event": "state", "node_id": nid,
                               "state": new_state})

    async def handle_drain_node(self, conn, data):
        """Graceful node drain (docs/autoscaler.md):

        ACTIVE -> DRAINING (durable)  — the raylet stops taking leases
          -> raylet ``drain`` RPC     — sealed primaries + spill blobs
                                        migrate to ACTIVE peers
        -> DRAINED (durable, success) — safe to terminate, or
        -> ACTIVE  (abort on failure) — the node keeps serving.

        ``force=True`` keeps the PR-≤15 semantics (immediate removal,
        used for crash simulation and last-resort eviction)."""
        node_id = NodeID(data["node_id"])
        reason = data.get("reason", "drained")
        info = self.nodes.get(node_id)
        if data.get("force") or info is None or not info.alive \
                or self.config.drain_timeout_s <= 0:
            self._mark_node_dead(node_id, reason)
            return {"drained": True, "forced": True}
        if info.state == NODE_DRAINED:
            return {"drained": True, "migrated": 0}  # idempotent retry
        if node_id in self._drains_inflight:
            return {"drained": False, "error": "drain in progress"}
        if info.state == NODE_ACTIVE:
            self._set_node_state(info, NODE_DRAINING, reason)
            await self._wal_flush()  # verdict durable before migrating
        # else: WAL-restored DRAINING after a GCS restart — re-enter
        self._drains_inflight.add(node_id)
        try:
            peers = [{"node_id": n.node_id.binary(),
                      "address": list(n.raylet_address)}
                     for n in self.nodes.values()
                     if n.alive and n.state == NODE_ACTIVE
                     and n.node_id != node_id]
            reply: Dict[str, Any] = {}
            err = None
            try:
                # failpoint: the migration leg fails — the drain must
                # ABORT and the node must return to ACTIVE, still
                # serving (acceptance: an aborted migration leaves the
                # node in service, never half-drained)
                _fp.failpoint("gcs.node_drain.migrate_fail")
                node_conn = self._node_conns.get(node_id)
                if node_conn is None:
                    raise RuntimeError("no raylet connection")
                reply = await node_conn.call(
                    "drain", {"peers": peers, "reason": reason},
                    timeout=self.config.drain_timeout_s) or {}
                if not reply.get("ok"):
                    raise RuntimeError(
                        reply.get("error", "raylet drain failed"))
            except Exception as e:  # noqa: BLE001 — abort the drain
                err = str(e) or type(e).__name__
            if err is not None:
                if info.alive and info.state == NODE_DRAINING:
                    self._set_node_state(info, NODE_ACTIVE,
                                         f"drain aborted: {err}")
                    await self._wal_flush()
                logger.warning("drain of node %s aborted: %s",
                               node_id.hex()[:12], err)
                return {"drained": False, "error": err}
            if not info.alive:  # died mid-migration
                return {"drained": False, "error": "node died mid-drain"}
            self._set_node_state(info, NODE_DRAINED, reason)
            await self._wal_flush()
            return {"drained": True,
                    "migrated": reply.get("migrated", 0),
                    "spill_handed_off": reply.get("spill_handed_off", 0)}
        finally:
            self._drains_inflight.discard(node_id)

    # ------------------------------------------------------------------
    # per-job quota table (fair-queue weights + in-flight ceilings)
    # ------------------------------------------------------------------
    async def handle_set_job_quota(self, conn, data):
        """Install/update/remove one job's scheduling quota.  The table
        is WAL- and snapshot-covered; raylets learn within one beat via
        the health-report ack (plus an immediate pubsub nudge)."""
        job = data["job"]
        quota = data.get("quota")
        if quota is None:
            self.quotas.pop(job, None)
        else:
            # normalize through JobQuota so malformed payloads fail
            # here, at the API boundary, not inside a raylet
            self.quotas[job] = JobQuota.from_dict(quota).to_dict()
        self._wal_append("quota", (job, self.quotas.get(job)))
        self._schedule_persist()
        await self._wal_flush()
        self.publish("quotas", {"quotas": dict(self.quotas)})
        return True

    async def handle_get_job_quotas(self, conn, data):
        return {"quotas": dict(self.quotas),
                "lease_tables": {n: dict(t)
                                 for n, t in self.lease_tables.items()}}

    def _event_append(self, record: Dict[str, Any]) -> None:
        """Route one event record into its severity's retention ring,
        counting displaced records (the old single shared ring let an
        INFO flood silently evict the ERROR evidence incidents need)."""
        sev = record.get("severity") or "INFO"
        ring = self._event_rings.get(sev)
        if ring is None:
            from collections import deque as _deque
            cap = max(16, int(getattr(self.config,
                                      "event_ring_size", 5000)))
            ring = self._event_rings[sev] = _deque(maxlen=cap)
        if len(ring) == ring.maxlen:
            self._events_evicted += 1
            _tm.events_evicted(1)
        ring.append(record)

    def _emit_event(self, severity: str, label: str, message: str,
                    **fields: Any) -> None:
        self._event_append(
            self._event_mod.emit(severity, label, message, **fields))

    def push_cluster_events(self, conn, record) -> None:
        """Event records pushed by raylets/workers (see util/event.py)."""
        self._event_append(record)

    async def handle_list_events(self, conn, data):
        severity = (data or {}).get("severity")
        limit = (data or {}).get("limit", 1000)
        if severity is not None:
            out = list(self._event_rings.get(severity, ()))
        else:
            out = sorted(
                (e for ring in self._event_rings.values() for e in ring),
                key=lambda e: e.get("timestamp", 0.0))
        return out[-limit:]

    # ------------------------------------------------------------------
    # incident journal (docs/observability.md "Incidents and
    # postmortems"): auto-opened on deaths / firing alerts, linked into
    # the other observability planes, WAL-persisted like alerts
    # ------------------------------------------------------------------
    def _open_or_merge_incident(self, kind: str, title: str,
                                severity: str = "error",
                                node: Optional[str] = None,
                                job: Optional[str] = None,
                                deployment: Optional[str] = None
                                ) -> Dict[str, Any]:
        """One incident per failure episode: a death/alert within
        ``incident_window_s`` of the newest incident's last update
        folds into it (a gang death is one incident, not N), otherwise
        a new incident opens.  Both paths WAL the full incident and
        (re)arm the delayed link collection."""
        now = time.time()
        window_s = float(getattr(self.config, "incident_window_s",
                                 120.0))
        inc: Optional[Dict[str, Any]] = None
        if self._incidents:
            newest = next(reversed(self._incidents.values()))
            if now - newest["last_update"] <= window_s:
                inc = newest
        if inc is None:
            inc = {
                "id": f"inc-{os.urandom(6).hex()}",
                "kind": kind, "title": title, "severity": severity,
                "opened_at": now, "last_update": now,
                "state": "open",
                # the window opens a beat early: the evidence that
                # explains a death precedes it
                "window": [now - 30.0, None],
                "nodes": [], "jobs": [], "deployments": [],
                "deaths": [], "alerts": [], "partial": False,
                "links": {},
            }
            cap = max(4, int(getattr(self.config,
                                     "incident_table_size", 200)))
            while len(self._incidents) >= cap:
                old_id, _ = self._incidents.popitem(last=False)
                self._incident_collect_handles.pop(old_id, None)
            self._incidents[inc["id"]] = inc
            _tm.incident_opened(kind)
            self._emit_event(
                "ERROR" if severity == "error" else "WARNING",
                "INCIDENT_OPEN", f"incident {inc['id']}: {title}",
                incident_id=inc["id"], kind=kind)
            logger.warning("incident %s opened: %s", inc["id"], title)
        else:
            inc["last_update"] = now
            if severity == "error":
                inc["severity"] = "error"
        if node and node not in inc["nodes"]:
            inc["nodes"].append(node)
        if job and job not in inc["jobs"]:
            inc["jobs"].append(job)
        if deployment and deployment not in inc["deployments"]:
            inc["deployments"].append(deployment)
        _flight.record("mark", f"incident {inc['id']}: {title}")
        self._incident_wal(inc)
        self._schedule_incident_collect(inc["id"])
        return inc

    def _incident_wal(self, inc: Dict[str, Any]) -> None:
        self._wal_append("incident", dict(inc))
        self._schedule_persist()

    def _incident_add_death(self, inc: Dict[str, Any], source: str,
                            pid: int, node: Optional[str], reason: str,
                            frames: List[Dict[str, Any]], torn: int,
                            partial: bool) -> None:
        """Attach one dead process's identity + flight tail.  The
        ``gcs.incident.collect_fail`` failpoint models the tail being
        lost mid-death-notification: the death entry still lands (the
        incident opens regardless), only the frames are gone and the
        incident is marked partial — the death path never wedges."""
        if _fp.active() and _fp.failpoint("gcs.incident.collect_fail"):
            frames, torn, partial = [], 0, True
        for d in inc["deaths"]:
            if d["pid"] == pid and d["source"] == source:
                if frames and not d["frames"]:
                    d["frames"], d["torn"] = frames, torn
                    d["partial"] = partial
                return
        inc["deaths"].append({
            "source": source, "pid": pid, "node": node,
            "reason": reason, "frames": frames, "torn": torn,
            "partial": partial, "ts": time.time()})
        if partial:
            inc["partial"] = True
        if frames:
            _tm.flight_tail_shipped(1)

    def _schedule_incident_collect(self, inc_id: str) -> None:
        """(Re)arm the delayed link-collection pass: it runs one flush
        period after the incident last moved, so the traces/metrics the
        episode produced have reached the GCS tables before we snapshot
        the links."""
        settle = float(getattr(self.config, "metrics_report_period_s",
                               5.0)) + 2.0
        old = self._incident_collect_handles.pop(inc_id, None)
        if old is not None:
            old.cancel()
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # unit tests building a GCS outside a loop
        def _fire() -> None:
            self._incident_collect_handles.pop(inc_id, None)
            t = loop.create_task(self._collect_incident(inc_id))
            t.add_done_callback(lambda t: t.exception())
        self._incident_collect_handles[inc_id] = loop.call_later(
            settle, _fire)

    async def _collect_incident(self, inc_id: str) -> None:
        """Fill the incident's links into the other planes: retained
        traces in the window, the firing-alert set, metrics-history
        slices, profiler/recovery state.  Re-runs on merge; every pass
        re-WALs the full incident (full-value set semantics)."""
        inc = self._incidents.get(inc_id)
        if inc is None:
            return
        try:
            now = time.time()
            since = inc["window"][0]
            traces = []
            for trace_id, entry in reversed(self._traces.items()):
                if entry.get("keep") is False:
                    continue
                row = self._trace_summary(trace_id, entry)
                if (row["start"] or 0.0) >= since:
                    traces.append(row)
                if len(traces) >= 50:
                    break
            series = {}
            for name in ("cluster:alive_nodes", "cluster:actors_alive"):
                rows = self._history.query(series=name, since=since)
                if rows:
                    series[name] = rows[0].get("points", [])
            inc["window"][1] = now
            inc["links"] = {
                "trace_ids": [t["trace_id"] for t in traces],
                "traces": traces,
                "alerts_firing": self._history.firing(),
                "timeseries": series,
                "profile_records": len(self._profile),
                "recovery": dict(self._recovery),
            }
            inc["state"] = "collected"
            self._incident_wal(inc)
        except Exception:  # noqa: BLE001 — forensics never wedges
            logger.exception("incident %s link collection failed",
                             inc_id)
            inc["partial"] = True
            inc["state"] = "collected"
            self._incident_wal(inc)

    # replay-safe by construction, not by a seq guard: a retried
    # delivery merges into the incident it just opened (same episode
    # window) and _incident_add_death dedupes on (source, pid), so the
    # INCIDENT_OPEN event emits at most once per episode
    # rtpu-check: disable=retry-safety
    async def handle_report_flight_tail(self, conn, data):
        """Death-notification path: a surviving raylet (or the head
        supervisor) shipped a dead process's flight-ring tail.  Opens
        or merges an incident; the tail attach is failpoint-gated but
        the incident itself always lands."""
        source = data["source"]
        pid = int(data["pid"])
        reason = data.get("reason") or "process died"
        node = data.get("node_id")
        node_hex = node.hex() if isinstance(node, bytes) else node
        inc = self._open_or_merge_incident(
            "death", f"{source} (pid {pid}) died: {reason}",
            node=node_hex)
        self._incident_add_death(
            inc, source, pid, node_hex, reason,
            list(data.get("frames") or []), int(data.get("torn") or 0),
            partial=not data.get("frames"))
        self._incident_wal(inc)
        await self._wal_flush()
        return {"incident_id": inc["id"]}

    @staticmethod
    def _incident_summary(inc: Dict[str, Any]) -> Dict[str, Any]:
        return {
            "id": inc["id"], "kind": inc["kind"], "title": inc["title"],
            "severity": inc["severity"], "state": inc["state"],
            "opened_at": inc["opened_at"],
            "last_update": inc["last_update"],
            "partial": inc.get("partial", False),
            "nodes": list(inc["nodes"]), "jobs": list(inc["jobs"]),
            "deployments": list(inc["deployments"]),
            "n_deaths": len(inc["deaths"]),
            "n_alerts": len(inc["alerts"]),
            "n_traces": len((inc.get("links") or {}).get("trace_ids",
                                                         ())),
        }

    async def handle_list_incidents(self, conn, data):
        data = data or {}
        kind = data.get("kind")
        limit = int(data.get("limit") or 50)
        out = [self._incident_summary(inc)
               for inc in reversed(self._incidents.values())
               if kind is None or inc["kind"] == kind]
        return out[:limit]

    async def handle_get_incident(self, conn, data):
        inc_id = data["incident_id"]
        inc = self._incidents.get(inc_id)
        if inc is None:
            # prefix match (CLI convenience, like trace ids)
            for iid, candidate in reversed(self._incidents.items()):
                if iid.startswith(inc_id):
                    inc = candidate
                    break
        return dict(inc) if inc is not None else None

    def _read_dead_raylet_ring(self, inc: Dict[str, Any],
                               info: "NodeInfo", reason: str) -> None:
        """Same-host node death: the GCS itself reads the dead raylet's
        ring from the session dir (there is no surviving raylet on that
        node to ship it)."""
        if not info.pid or not self._session_dir:
            return
        try:
            for path in _flight.rings_for_pid(self._session_dir,
                                              info.pid):
                tail = _flight.read_ring(path)
                if tail is not None:
                    self._incident_add_death(
                        inc, tail["source"], info.pid,
                        info.node_id.hex(), reason,
                        tail["frames"][-200:], tail["torn"],
                        partial=False)
                try:
                    os.unlink(path)
                except OSError:
                    pass
        except Exception:  # noqa: BLE001 — forensics never wedges
            logger.exception("dead raylet ring read failed")
            inc["partial"] = True

    def _mark_node_dead(self, node_id: NodeID, reason: str) -> None:
        info = self.nodes.get(node_id)
        if info is None or not info.alive:
            return
        info.alive = False
        info.state = NODE_DEAD
        info.resources_available = {}
        self._node_conns.pop(node_id, None)
        # the node_dead record also clears any durable drain verdict
        # and lease table on replay (_wal_apply) — mirror in memory
        self._node_states.pop(node_id.binary(), None)
        self.lease_tables.pop(node_id.hex(), None)
        self._wal_append("node_dead", {"node_id": node_id.binary()})
        _tm.node_death()
        logger.warning("node %s dead: %s", node_id.hex()[:12], reason)
        self._mark_sync_dirty(node_id)
        self._emit_event("ERROR", "NODE_DEAD",
                         f"node {node_id.hex()[:12]} dead: {reason}",
                         node_id=node_id.hex())
        _flight.record("node_dead",
                       f"{node_id.hex()[:12]} {reason}")
        # incident journal: a node death always opens (or joins) an
        # incident; the dead raylet's own flight ring is read here —
        # no surviving process on that node will ship it
        try:
            inc = self._open_or_merge_incident(
                "death", f"node {node_id.hex()[:12]} dead: {reason}",
                node=node_id.hex())
            self._read_dead_raylet_ring(inc, info, reason)
            self._incident_wal(inc)
        except Exception:  # noqa: BLE001 — never wedge the death path
            logger.exception("incident open failed for node death")
        # failpoint: the death broadcast is lost — consumers must
        # converge via the versioned resource-view sync (gap → resync)
        # instead of trusting one pubsub delivery
        if not _fp.failpoint("gcs.node_death.publish_drop"):
            self.publish("nodes",
                         {"event": "dead", "node_id": node_id.binary(),
                          "address": info.raylet_address})
        # fail actors on the node (restart if budget remains)
        for actor in list(self.actors.values()):
            if actor.node_id == node_id and actor.state in (ACTOR_ALIVE,
                                                            ACTOR_PENDING):
                self._on_actor_worker_lost(actor.actor_id,
                                           f"node died: {reason}")
        # placement groups with bundles there must be rescheduled
        for pg in self.placement_groups.values():
            if pg.state == "CREATED" and node_id in pg.bundle_nodes.values():
                pg.state = "RESCHEDULING"
                asyncio.get_running_loop().create_task(self._schedule_pg(pg))

    async def _health_check_loop(self) -> None:
        while True:
            await asyncio.sleep(self.config.health_report_period_s)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.alive and (now - node.last_heartbeat
                                   > self.config.health_timeout_s):
                    self._mark_node_dead(node.node_id, "health check timeout")

    # ------------------------------------------------------------------
    # KV store (GcsInternalKVManager)
    # ------------------------------------------------------------------
    async def handle_kv_put(self, conn, data):
        ns_name = data.get("namespace", "")
        ns = self.kv.setdefault(ns_name, {})
        existed = data["key"] in ns
        overwrite = data.get("overwrite", True)
        if overwrite or not existed:
            ns[data["key"]] = data["value"]
            self._wal_append("kv_put", (ns_name, data["key"],
                                        data["value"], overwrite))
        self._schedule_persist()
        await self._wal_flush()  # the ack promises durability
        return existed

    async def handle_kv_get(self, conn, data):
        return self.kv.get(data.get("namespace", ""), {}).get(data["key"])

    async def handle_kv_del(self, conn, data):
        ns_name = data.get("namespace", "")
        ns = self.kv.get(ns_name, {})
        existed = ns.pop(data["key"], None) is not None
        if existed:
            self._wal_append("kv_del", (ns_name, data["key"]))
        self._schedule_persist()
        await self._wal_flush()
        return existed

    async def handle_kv_keys(self, conn, data):
        ns = self.kv.get(data.get("namespace", ""), {})
        prefix = data.get("prefix", "")
        return [k for k in ns if k.startswith(prefix)]

    # ------------------------------------------------------------------
    # function table (GcsFunctionManager)
    # ------------------------------------------------------------------
    async def handle_register_function(self, conn, data):
        self.functions[data["function_id"]] = data["blob"]
        self._wal_append("function", (data["function_id"], data["blob"]))
        self._schedule_persist()
        await self._wal_flush()
        return True

    async def handle_get_function(self, conn, data):
        return self.functions.get(data["function_id"])

    # ------------------------------------------------------------------
    # jobs (GcsJobManager)
    # ------------------------------------------------------------------
    def _wal_job(self, job_id: JobID) -> None:
        job = self.jobs.get(job_id)
        if job is not None:
            self._wal_append("job", (job_id.binary(), dict(job),
                                     self.job_counter))

    async def handle_register_job(self, conn, data):
        self.job_counter += 1
        job_id = JobID.from_int(self.job_counter)
        self.jobs[job_id] = {"start_time": time.time(),
                             "driver_address": data.get("driver_address"),
                             "alive": True}
        self._wal_job(job_id)
        self._schedule_persist()
        await self._wal_flush()  # the id is live the moment we reply
        return {"job_id": job_id.binary()}

    async def handle_reattach_job(self, conn, data):
        """A driver reconnecting after a head restart re-announces its
        (persisted) job instead of minting a new id."""
        job_id = JobID(data["job_id"])
        job = self.jobs.get(job_id)
        if job is None:
            # snapshot predates the job (e.g. memory storage): recreate
            job = {"start_time": time.time()}
            self.jobs[job_id] = job
            self.job_counter = max(self.job_counter, job_id.int_value())
        job["alive"] = True
        job["driver_address"] = data.get("driver_address")
        self._wal_job(job_id)
        self._schedule_persist()
        await self._wal_flush()
        return {"job_id": job_id.binary()}

    async def handle_job_finished(self, conn, data):
        job_id = JobID(data["job_id"])
        job = self.jobs.get(job_id)
        if job:
            job["alive"] = False
            job["end_time"] = time.time()
            self._wal_job(job_id)
        self._schedule_persist()
        await self._wal_flush()
        return True

    # ------------------------------------------------------------------
    # task events (state API feed; parity: TaskEventBuffer -> GCS)
    # ------------------------------------------------------------------
    async def handle_report_task_events(self, conn, data):
        seq = data.get("seq")
        if seq is not None:
            # the pool re-sends this method after a timed-out ack
            # (IDEMPOTENT_METHODS), but extend/counter folds below do
            # NOT converge on replay — drop any batch at or below the
            # reporting worker's high-water flush seq
            src = data.get("source") or ""
            if self._task_event_seq.get(src, -1) >= seq:
                return True
            self._task_event_seq[src] = seq
        self._task_events.extend(data["events"])
        # monotonic counter for the metrics surface: the ring buffer
        # rotates, so counting FINISHED entries in it is not a counter
        self._tasks_finished_total += sum(
            1 for e in data["events"] if e.get("state") == "FINISHED")
        overflow = len(self._task_events) - self.config.task_events_buffer_size
        if overflow > 0:
            # ring-buffer eviction is DATA LOSS for the state API —
            # count it per job and surface it (debug_state, metrics)
            # instead of deleting silently
            for ev in self._task_events[:overflow]:
                job = ev.get("job_id") or "unknown"
                self._task_event_drops[job] = \
                    self._task_event_drops.get(job, 0) + 1
                _tm.task_events_dropped(job, 1)
            self._task_event_drops_total += overflow
            del self._task_events[:overflow]
            now = time.monotonic()
            if not self._drop_burst_started or \
                    now - self._drop_burst_started > 10.0:
                # log once per overflow burst, not once per batch — a
                # sustained storm would otherwise flood the log
                if self._drop_burst_count:
                    logger.warning(
                        "previous task-event overflow burst dropped %d "
                        "events", self._drop_burst_count)
                logger.warning(
                    "task-event buffer full (%d): dropping oldest events "
                    "(per-job counts in debug_state; raise "
                    "task_events_buffer_size to keep more)",
                    self.config.task_events_buffer_size)
                self._drop_burst_count = 0
            self._drop_burst_started = now
            self._drop_burst_count += overflow
        return True

    # ------------------------------------------------------------------
    # metrics aggregation (parity: MetricsAgent / OpenCensus proxy
    # collector metrics_agent.py:188,374 — here the GCS is the hub)
    # ------------------------------------------------------------------
    def _ingest_metrics(self, records) -> None:
        """Fold one process's flush batch into the cluster table:
        counters/histograms accumulate, gauges replace.  ``_ts`` stamps
        each entry so stale gauges (dead workers' last values) age out
        of the export instead of lingering forever."""
        now = time.monotonic()
        for rec in records:
            key = (rec["name"], tuple(sorted(rec.get("tags", {}).items())))
            cur = self._metrics.get(key)
            if rec["type"] == "counter":
                if cur is None:
                    cur = dict(rec)
                else:
                    cur["value"] += rec["value"]
            elif rec["type"] == "gauge":
                cur = dict(rec)
            elif rec["type"] == "histogram":
                if cur is None:
                    cur = dict(rec)
                else:
                    cur["buckets"] = [a + b for a, b in
                                      zip(cur["buckets"], rec["buckets"])]
                    cur["sum"] += rec["sum"]
                    cur["count"] += rec["count"]
                    if rec.get("exemplars"):
                        # per-bucket exemplars: newest flush wins
                        ex = dict(cur.get("exemplars") or {})
                        ex.update(rec["exemplars"])
                        cur["exemplars"] = ex
            else:
                continue
            cur["_ts"] = now
            self._metrics[key] = cur

    #: gauges older than this stop being exported (their process is gone
    #: or stopped flushing); cumulative series are kept forever
    _GAUGE_STALE_S = 120.0

    async def handle_report_metrics(self, conn, data):
        seq = data.get("seq")
        if seq is not None:
            # counters/histograms ACCUMULATE in _ingest_metrics, so a
            # replayed flush (retry after a lost ack) double-counts —
            # drop batches at or below the source's high-water seq
            src = data.get("source") or ""
            if self._metric_seq.get(src, -1) >= seq:
                return True
            self._metric_seq[src] = seq
        self._ingest_metrics(data.get("records", []))
        return True

    def _sweep_stale_metrics(self) -> None:
        """Periodic stale-gauge pruning (a dead process's last value
        must age out of the export).  Lives on the history tick — NOT
        in the read handler, which used to delete entries mid-iteration
        and would race the history sampler reading the same table."""
        now = time.monotonic()
        for key, rec in list(self._metrics.items()):
            if rec["type"] == "gauge" and \
                    now - rec.get("_ts", now) > self._GAUGE_STALE_S:
                del self._metrics[key]

    async def handle_get_metrics(self, conn, data):
        # side-effect free (stale pruning happens in the periodic
        # sweep): a read RPC must never mutate the table other readers
        # and the history sampler iterate
        return [{k: v for k, v in rec.items() if k != "_ts"}
                for rec in self._metrics.values()]

    # ------------------------------------------------------------------
    # metrics history + alerting (core/metrics_history.py)
    # ------------------------------------------------------------------
    async def _history_loop(self) -> None:
        """Sample tick of the cluster health plane: prune stale gauges,
        fold the merged table into the history rings, re-evaluate
        recording + alert rules, publish transitions, persist the
        firing set.  A failed sample tick (failpoint
        ``gcs.metrics_history.sample_fail``) skips the fold only — the
        evaluator still runs, so alerting survives ingest trouble."""
        hist = self._history
        while True:
            await asyncio.sleep(hist.interval_s)
            now = time.time()
            try:
                self._sweep_stale_metrics()
                try:
                    if _fp.failpoint("gcs.metrics_history.sample_fail"):
                        raise _fp.FailpointError(
                            "gcs.metrics_history.sample_fail")
                    hist.sample(self._metrics, now=now)
                    # tick-local cluster gauges: these must not depend
                    # on any process's flush loop being alive
                    hist.observe("cluster:alive_nodes", sum(
                        1 for n in self.nodes.values() if n.alive), now)
                    hist.observe("cluster:actors_alive", sum(
                        1 for a in self.actors.values()
                        if a.state == ACTOR_ALIVE), now)
                except Exception:  # noqa: BLE001 — skip, never wedge
                    hist.sample_failures += 1
                    _tm.history_sample_failure()
                transitions = hist.evaluate(now=now)
                st = hist.stats()
                _tm.history_stats(
                    st["points"], st["series"],
                    hist.evicted_total - self._history_evicted_reported)
                self._history_evicted_reported = hist.evicted_total
                _tm.alerts_stats(st["alerts_firing"], len(transitions))
                if transitions:
                    self._on_alert_transitions(transitions)
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — the loop must survive
                logger.exception("metrics history tick failed")

    def _on_alert_transitions(self, transitions) -> None:
        """Publish each transition on the ``alerts`` channel + event
        log, then persist the new firing set so it survives a head
        restart (the WAL record rides the next handler group-commit)."""
        import json as _json

        for t in transitions:
            self.publish("alerts", t)
            sev = "INFO" if t["to"] == "resolved" else (
                "ERROR" if t["severity"] == "critical" else "WARNING")
            tag_txt = " ".join(f"{k}={v}"
                               for k, v in sorted(t["tags"].items()))
            self._emit_event(
                sev, "ALERT_" + t["to"].upper(),
                f"alert {t['rule']} {t['from']} -> {t['to']}"
                + (f" ({tag_txt})" if tag_txt else "")
                + (f" value={t['value']:.4g}"
                   if t.get("value") is not None else ""),
                rule=t["rule"], **t["tags"])
        blob = _json.dumps(self._history.export_firing()).encode()
        self.kv.setdefault("_internal", {})[ALERTS_FIRING_KV_KEY] = blob
        self._wal_append("kv_put", ("_internal", ALERTS_FIRING_KV_KEY,
                                    blob, True))
        self._schedule_persist()
        for t in transitions:
            _flight.record("alert",
                           f"{t['rule']} {t['from']} -> {t['to']}")
        # incident journal: a firing transition opens (or joins) an
        # incident; re-WALed with the transition attached
        firing = [t for t in transitions if t["to"] == "firing"]
        if firing:
            try:
                sev = "error" if any(t["severity"] == "critical"
                                     for t in firing) else "warning"
                inc = self._open_or_merge_incident(
                    "alert",
                    "alert firing: " + ", ".join(
                        sorted({t["rule"] for t in firing})),
                    severity=sev)
                inc["alerts"].extend(firing)
                self._incident_wal(inc)
            except Exception:  # noqa: BLE001 — alerting must survive
                logger.exception("incident open failed for alerts")

    async def handle_get_timeseries(self, conn, data):
        data = data or {}
        return self._history.query(
            series=data.get("series"), since=data.get("since"),
            limit=int(data.get("limit") or 200))

    async def handle_get_alerts(self, conn, data):
        out = self._history.alerts_view()
        out["stats"] = self._history.stats()
        return out

    async def handle_healthz(self, conn, data):
        """One-word cluster verdict for probes: ``ok`` (nothing
        firing), ``degraded`` (warnings firing or persistence
        degraded), ``critical`` (a critical alert is firing)."""
        firing = self._history.firing()
        critical = [a["rule"] for a in firing
                    if a["severity"] == "critical"]
        degraded = bool(firing) or self._wal_degraded \
            or self.table_storage.persist_failures > 0
        status = "critical" if critical else (
            "degraded" if degraded else "ok")
        open_incidents = [i for i in self._incidents.values()
                          if i["state"] == "open"]
        return {
            "ok": not critical,
            "status": status,
            "firing": [a["rule"] for a in firing],
            "alive_nodes": sum(1 for n in self.nodes.values()
                               if n.alive),
            "wal_degraded": self._wal_degraded,
            "persist_failures": self.table_storage.persist_failures,
            "incidents": len(self._incidents),
            "incidents_open": len(open_incidents),
            "last_incident": next(
                reversed(self._incidents.values()))["id"]
            if self._incidents else None,
        }

    async def handle_report_spans(self, conn, data):
        self._spans.extend(data.get("spans", []))
        return True

    async def handle_get_spans(self, conn, data):
        limit = (data or {}).get("limit")
        if limit is None:
            limit = 20000
        if limit <= 0:  # out[-0:] would be the WHOLE table
            return []
        cat = (data or {}).get("cat")
        out = [s for s in self._spans if cat is None or s.get("cat") == cat]
        return out[-limit:]

    async def handle_clock_sync(self, conn, data):
        """Timebase for span alignment: reporters NTP-probe this and
        correct their span timestamps onto the GCS wall clock."""
        return {"time": time.time()}

    # ------------------------------------------------------------------
    # distributed tracing plane (core/tracing.py -> trace ring)
    # ------------------------------------------------------------------
    def _tail_keep(self, trace_id: str, root: Dict[str, Any]) -> bool:
        """Tail-sampling decision, made at trace COMPLETION (the root
        span's arrival), never at ingress: anything anomalous is kept
        in full, fast successes keep a deterministic fraction (hash of
        the trace id, so every process agrees without coordination).
        ``unknown_deployment`` (bad URLs) is client junk, not an
        anomaly — it samples like a success so scanners can't evict
        the real SLO-miss evidence from the bounded ring."""
        if root.get("status", "ok") not in ("ok", "unknown_deployment"):
            return True
        tags = root.get("tags") or {}
        if tags.get("slo_miss") or tags.get("retried"):
            return True
        frac = float(getattr(self.config,
                             "trace_sample_keep_fraction", 0.05))
        if frac >= 1.0:
            return True
        if frac <= 0.0:
            return False
        try:
            return (int(trace_id[:8], 16) % 10000) < frac * 10000
        except ValueError:
            return True  # unhashable id: keep rather than lose signal

    def _note_trace_evicted(self, trace_id: str) -> None:
        if len(self._trace_evicted_ids) >= 8192:
            self._trace_evicted_set.discard(
                self._trace_evicted_ids.popleft())
        self._trace_evicted_ids.append(trace_id)
        self._trace_evicted_set.add(trace_id)

    def _trace_entry(self, trace_id: str) -> Dict[str, Any]:
        entry = self._traces.get(trace_id)
        if entry is None:
            cap = max(16, int(getattr(self.config,
                                      "trace_table_size", 2000)))
            while len(self._traces) >= cap:
                old_id, old = self._traces.popitem(last=False)
                self._note_trace_evicted(old_id)
                if old.get("spans") or old.get("keep") is None:
                    self._traces_evicted += 1
                    _tm.trace_evicted(1)
            entry = self._traces[trace_id] = {
                "spans": [], "keep": None, "root": None,
                "first": time.time(), "truncated": 0}
        return entry

    def _ingest_trace_span(self, span: Dict[str, Any]) -> None:
        trace_id = span.get("trace_id")
        if not trace_id:
            return
        if trace_id not in self._traces \
                and trace_id in self._trace_evicted_set:
            return  # straggler of an evicted trace: gone is gone
        entry = self._trace_entry(trace_id)
        if entry["keep"] is False:
            return  # sampled out: stragglers drop against the tombstone
        if len(entry["spans"]) >= self._trace_span_cap \
                and not span.get("root"):
            # the root is load-bearing (tail-sampling decision, tree
            # anchor, telescoping) — it lands even past the cap
            entry["truncated"] += 1
        else:
            entry["spans"].append(span)
        if span.get("root"):
            entry["root"] = span
            keep = self._tail_keep(trace_id, span)
            entry["keep"] = keep
            if keep:
                self._traces_retained += 1
                _tm.trace_retained(1)
            else:
                entry["spans"] = []
                self._traces_sampled_out += 1
                _tm.trace_sampled_out(1)

    async def handle_report_trace_spans(self, conn, data):
        # failpoint: the trace ingest drops a batch — reporters must not
        # notice (drop-don't-block); only the assembled tree is poorer
        if _fp.active() and _fp.failpoint("gcs.report_spans.trace_drop"):
            return True
        spans = data.get("spans", [])
        _tm.trace_spans_ingested(len(spans))
        for span in spans:
            self._ingest_trace_span(span)
        return True

    def _find_trace(self, trace_id: str
                    ) -> Tuple[Optional[str], Optional[Dict[str, Any]]]:
        entry = self._traces.get(trace_id)
        if entry is not None:
            return trace_id, entry
        # prefix match (CLI convenience: ids print truncated)
        for tid, e in self._traces.items():
            if tid.startswith(trace_id):
                return tid, e
        return None, None

    @staticmethod
    def _trace_summary(trace_id: str, entry: Dict[str, Any]
                       ) -> Dict[str, Any]:
        root = entry.get("root")
        tags = (root or {}).get("tags") or {}
        return {
            "trace_id": trace_id,
            "name": root.get("name") if root else None,
            "status": root.get("status") if root else "incomplete",
            "start": root.get("start") if root
            else entry.get("first"),
            "duration_s": (root["end"] - root["start"]) if root else None,
            "deployment": tags.get("deployment"),
            "slo_miss": bool(tags.get("slo_miss")),
            "retried": bool(tags.get("retried")),
            "n_spans": len(entry.get("spans", [])),
            "complete": root is not None,
        }

    async def handle_get_trace(self, conn, data):
        trace_id, entry = self._find_trace(data["trace_id"])
        if entry is None:
            return None
        if entry.get("keep") is False:
            return {"trace_id": trace_id, "sampled_out": True,
                    "spans": []}
        spans = sorted(entry["spans"], key=lambda s: s.get("start", 0.0))
        out = self._trace_summary(trace_id, entry)
        out["spans"] = spans
        out["truncated_spans"] = entry.get("truncated", 0)
        return out

    async def handle_list_traces(self, conn, data):
        data = data or {}
        deployment = data.get("deployment")
        slo_only = bool(data.get("slo_misses"))
        since = data.get("since")
        until = data.get("until")
        limit = data.get("limit") or 100
        out = []
        for trace_id, entry in reversed(self._traces.items()):
            if entry.get("keep") is False:
                continue
            row = self._trace_summary(trace_id, entry)
            if deployment is not None \
                    and row["deployment"] != deployment:
                continue
            if slo_only and not (row["slo_miss"]
                                 or (row["complete"]
                                     and row["status"] != "ok")):
                continue
            if since is not None and (row["start"] or 0.0) < since:
                continue
            if until is not None and (row["start"] or 0.0) > until:
                continue
            out.append(row)
            if len(out) >= limit:
                break
        return out

    # ------------------------------------------------------------------
    # continuous profiling plane (core/profiler.py)
    # ------------------------------------------------------------------
    async def handle_report_profile(self, conn, data):
        # failpoint: the profile ingest drops a batch — the reporter
        # must not notice (drop-don't-block), only the ring is poorer
        if _fp.active() and _fp.failpoint("gcs.report_profile.drop"):
            return True
        records = data.get("records", [])
        overflow = len(self._profile) + len(records) \
            - (self._profile.maxlen or 0)
        if overflow > 0:
            # deque eviction is silent data loss for get_profile —
            # count it (debug_state + metrics) like task-event drops
            self._profile_evicted += overflow
            _tm.profiler_records_evicted(overflow)
        self._profile.extend(records)
        return True

    async def handle_get_profile(self, conn, data):
        """Merged profile view: fold every reporting process's records
        into one (stack, task, job)-keyed count table (the cluster
        flamegraph), optionally filtered by job / node / window."""
        from ray_tpu.core import profiler as profiler_mod

        data = data or {}
        job = data.get("job")
        node = data.get("node")
        since = data.get("since")
        limit = data.get("limit") or 10000
        rows = [r for r in self._profile
                if (job is None or r.get("job") == job)
                and (node is None
                     or (r.get("node") or "").startswith(node))
                and (since is None or r.get("end", 0) >= since)]
        sources = sorted({(r.get("node"), r.get("pid"))
                          for r in rows})
        merged = profiler_mod.merge_records(rows)[:limit]
        return {"records": merged,
                "total_samples": sum(r.get("count", 0) for r in merged),
                "sources": [{"node": n, "pid": p} for n, p in sources],
                "raw_records": len(rows)}

    async def handle_profiler_control(self, conn, data):
        """Arm/disarm the cluster profiling window: applies to the GCS
        process itself, then fans out to every alive raylet (each
        raylet fans out to its own workers)."""
        from ray_tpu.core import profiler as profiler_mod

        enabled = bool(data["enabled"])
        hz = data.get("hz")
        duration = data.get("duration_s")
        profiler_mod.configure(enabled, hz=hz, duration_s=duration)
        self._profiler_state = {
            "enabled": enabled, "hz": hz,
            "deadline": (time.monotonic() + float(duration)
                         if enabled and duration else None),
        } if enabled else None

        async def one(node):
            conn2 = self._node_conns.get(node.node_id)
            if conn2 is None or conn2.closed:
                return None
            try:
                return await asyncio.wait_for(
                    conn2.call("profiler_control", data), 10.0)
            except Exception:  # noqa: BLE001 — best-effort fan-out
                return None
        replies = await asyncio.gather(
            *(one(n) for n in list(self.nodes.values()) if n.alive))
        applied = [r for r in replies if r]
        return {"nodes_applied": len(applied),
                "workers_applied": sum(r.get("workers_applied", 0)
                                       for r in applied)}

    async def handle_list_jobs(self, conn, data):
        return [{"job_id": jid.hex(), **{k: v for k, v in j.items()}}
                for jid, j in self.jobs.items()]

    async def handle_get_task_events(self, conn, data):
        """Task-event rows, newest-last.  ``job_id``/``state`` filters
        and the limit apply HERE so consumers (state API list_tasks,
        the analyzer) stop shipping the whole ring over the wire and
        filtering client-side."""
        data = data or {}
        limit = data.get("limit", 1000)
        job_id = data.get("job_id")
        state = data.get("state")
        if job_id is None and state is None:
            return self._task_events[-limit:]
        out = [ev for ev in self._task_events
               if (job_id is None or ev.get("job_id") == job_id)
               and (state is None or ev.get("state") == state)]
        return out[-limit:]

    async def handle_get_cluster_stats(self, conn, data):
        """Cheap scalar gauges for the metrics surface (one dict, not a
        thousand event rows per scrape)."""
        return {
            "tasks_finished_total": self._tasks_finished_total,
            "alive_nodes": sum(1 for n in self.nodes.values() if n.alive),
            "actors_alive": sum(1 for a in self.actors.values()
                                if a.state == ACTOR_ALIVE),
            "task_event_drops_total": self._task_event_drops_total,
            "task_event_drops": dict(self._task_event_drops),
        }

    # ------------------------------------------------------------------
    # actor manager (GcsActorManager + GcsActorScheduler)
    # ------------------------------------------------------------------
    def _register_one_actor(self, conn, data
                            ) -> Tuple[Dict[str, Any],
                                       Optional[ActorInfo]]:
        """Table mutation of one actor registration (shared by the
        single and batched handlers).  Returns ``(reply, info)`` where
        ``info`` is the freshly-registered actor the caller must
        schedule, or ``None`` (replayed/existing registration — nothing
        to schedule).  A name conflict raises ``ValueError``.

        Idempotent keyed on ``actor_id``: a replayed registration (a
        retried batch whose first attempt executed but lost its reply)
        converges on the existing directory entry instead of minting a
        second creation task.
        """
        actor_id = ActorID(data["actor_id"])
        prior = self.actors.get(actor_id)
        if prior is not None:
            # replay: re-subscribe the (possibly reconnected) owner and
            # ack with the existing entry — never re-schedule
            self.subscribers.setdefault(
                f"actor:{actor_id.hex()}", set()).add(conn)
            return ({"existing": False, "actor_id": actor_id.binary(),
                     "subscribed": True}, None)
        name = data.get("name")
        namespace = data.get("namespace", "default")
        if name is not None:
            key = (namespace, name)
            existing_id = self.named_actors.get(key)
            if existing_id is not None and existing_id != actor_id:
                existing = self.actors.get(existing_id)
                if existing is not None and existing.state != ACTOR_DEAD:
                    if data.get("get_if_exists"):
                        return ({"existing": True,
                                 "actor_id": existing_id.binary()}, None)
                    raise ValueError(
                        f"actor name {name!r} already taken in {namespace!r}")
            self.named_actors[key] = actor_id
        info = ActorInfo(
            actor_id=actor_id,
            name=name,
            namespace=namespace,
            detached=data.get("detached", False),
            max_restarts=data.get("max_restarts", 0),
            creation_spec_blob=data["spec_blob"],
            resources=dict(data.get("resources", {})),
            owner_job=JobID(data["job_id"]),
            class_name=data.get("class_name", ""),
            pg_id=PlacementGroupID(data["placement_group_id"])
            if data.get("placement_group_id") else None,
            bundle_index=data.get("bundle_index", -1),
            strategy=data.get("strategy") or "DEFAULT",
            strategy_node=data.get("strategy_node"),
            strategy_soft=bool(data.get("strategy_soft", False)),
            env_hash=data.get("env_hash"),
            env_spawn=data.get("env_spawn"),
            locality=data.get("locality"),
        )
        self.actors[actor_id] = info
        # typed WAL record BEFORE the reply can leave (the handler
        # flushes): a registration acked into the snapshot debounce
        # window must survive an immediate SIGKILL, or the PR-9 storm
        # retry converges onto an entry that no longer exists
        self._wal_actor(info)
        self._schedule_persist()
        # auto-subscribe the registering owner to the actor's channel:
        # its submitter needs the ALIVE address anyway, and the explicit
        # subscribe + get_actor round trips cost two driver-side RTTs
        # PER ACTOR during creation storms
        self.subscribers.setdefault(
            f"actor:{actor_id.hex()}", set()).add(conn)
        return ({"existing": False, "actor_id": actor_id.binary(),
                 "subscribed": True}, info)

    async def handle_register_actor(self, conn, data):
        """Register + schedule an actor creation.

        ``data``: actor_id, creation spec blob (pickled TaskSpec),
        resources, name/namespace/detached, max_restarts, class_name.
        """
        # failpoint: GCS stalls/crashes mid-registration — the owner's
        # register future must resolve with a typed error or the retry
        # must converge on ONE directory entry (keyed on actor_id)
        await _fp.afailpoint("gcs.register_actor.stall")
        # traced registrations (the payload carried "trace", re-activated
        # by rpc dispatch) get a gcs.register_actor hop span
        _hop = _trace.start_span("gcs.register_actor")
        try:
            reply, info = self._register_one_actor(conn, data)
        except ValueError:
            if _hop is not None:
                _hop.end(status="error", outcome="name_conflict")
            raise
        if info is not None:
            self._spawn_schedule_task(info)
        if _hop is not None:
            _hop.end(outcome="existing" if reply.get("existing")
                     else None, actor=ActorID(data["actor_id"]).hex()[:12])
        await self._wal_flush()  # ack promises a durable registration
        return reply

    async def handle_register_actor_batch(self, conn, data):
        """Coalesced registration: one RPC registers a whole creation
        burst, then the batch schedules as ONE pipelined bring-up
        (node selection up front, lease fan-out grouped per raylet)
        instead of N independent lease round trips.

        Per-entry semantics match ``register_actor`` exactly — name
        conflicts become per-entry ``{"error": ...}`` replies so one
        bad entry cannot fail its batch-mates; replayed entries (the
        idempotent-retry case) ack against the existing directory
        entry without re-scheduling.
        """
        # failpoint: the batch is lost before ANY table mutation — the
        # owner's idempotent retry (keyed on actor_id) must converge on
        # exactly one directory entry per actor
        if _fp.active() and await _fp.afailpoint(
                "gcs.register_actor_batch.drop"):
            return None
        seq = data.get("seq")
        src = data.get("source") or ""
        if seq is not None:
            cached = self._reg_batch_acks.get(src)
            if cached is not None and cached[0] == seq:
                # replayed batch (the sender retries on a lost ack):
                # each entry is a keyed upsert already, but re-running
                # would double-count the batch telemetry and re-spawn
                # the scheduling task — re-serve the first pass's
                # replies verbatim
                return {"replies": cached[1]}
        entries = data["actors"]
        replies: List[Dict[str, Any]] = []
        to_schedule: List[ActorInfo] = []
        for entry in entries:
            # per-entry trace carrier: a traced creation inside a batch
            # still gets its gcs.register_actor hop span.  The context
            # is reset after the entry so one traced creation cannot
            # leak its attribution over batch-mates (or the shared
            # scheduling task spawned below)
            _hop = _tok = None
            if _trace.enabled() and entry.get("trace") is not None:
                _tok = _trace.set_current(_trace.ctx_of(entry["trace"]))
                _hop = _trace.start_span("gcs.register_actor")
            try:
                try:
                    reply, info = self._register_one_actor(conn, entry)
                except ValueError as e:
                    replies.append({"actor_id": entry["actor_id"],
                                    "error": str(e)})
                    if _hop is not None:
                        _hop.end(status="error", outcome="name_conflict")
                    continue
                replies.append(reply)
                if info is not None:
                    to_schedule.append(info)
                if _hop is not None:
                    _hop.end(outcome="existing" if reply.get("existing")
                             else None)
            finally:
                if _tok is not None:
                    _trace.reset_current(_tok)
        _tm.sched_registration_batch(len(entries))
        self._reg_batches += 1
        self._reg_batch_actors += len(entries)
        if to_schedule:
            t = asyncio.get_running_loop().create_task(
                self._schedule_actor_batch(to_schedule))
            t.add_done_callback(lambda t: t.exception())
        # ONE group-commit flush covers the whole batch's records: a
        # registration storm pays one fsync per batch, not per actor
        await self._wal_flush()
        if seq is not None:
            self._reg_batch_acks[src] = (seq, replies)
        return {"replies": replies}

    def _publish_actor(self, info: ActorInfo) -> None:
        # every published transition also reaches the durable table: the
        # snapshot persists the FULL actor table, so a detached-only gate
        # would leave non-detached actors stale across a head restart.
        # The WAL record is enqueued here (sync transition paths cannot
        # await); client-facing handlers flush before replying
        self._wal_actor(info)
        self._schedule_persist()
        channel = f"actor:{info.actor_id.hex()}"
        self.publish(channel, self._actor_message(info))
        if info.state == ACTOR_DEAD:
            # DEAD is terminal — nothing will be published here again.
            # Dropping the channel now (not at subscriber disconnect)
            # keeps a long-lived driver churning short-lived actors from
            # accreting one auto-subscribed entry per dead actor
            self.subscribers.pop(channel, None)

    def _actor_message(self, info: ActorInfo) -> Dict[str, Any]:
        return {
            "actor_id": info.actor_id.binary(),
            "state": info.state,
            "address": info.address,
            "node_id": info.node_id.binary() if info.node_id else None,
            "num_restarts": info.num_restarts,
            "death_cause": info.death_cause,
        }

    async def _schedule_actor(self, info: ActorInfo) -> None:
        """Pick a node, lease a worker there, push the creation task.

        Parity: GcsActorScheduler::Schedule (gcs_actor_scheduler.cc:49).
        """
        lock = self._actor_creation_locks.setdefault(info.actor_id,
                                                     asyncio.Lock())
        async with lock:
            deadline = time.monotonic() + 120.0
            while time.monotonic() < deadline:
                if info.state == ACTOR_DEAD:
                    return
                if info.state == ACTOR_ALIVE:
                    # a worker already announced (actor_started) —
                    # e.g. one that survived a head restart and
                    # re-registered while this reschedule task was
                    # pending, or a lease whose reply was lost but
                    # whose worker came up.  Leasing again would mint
                    # a SECOND living copy of the actor.
                    return
                pg = self.placement_groups.get(info.pg_id) \
                    if info.pg_id else None
                if info.pg_id is not None:
                    # gang-bound: the bundle's node is the only candidate,
                    # and the lease is charged to the bundle's reservation
                    # (the node pool already paid for it at prepare time)
                    if pg is None or pg.state == "REMOVED":
                        info.state = ACTOR_DEAD
                        info.death_cause = "placement group removed"
                        self._publish_actor(info)
                        return
                    if pg.state != "CREATED":
                        # placement in progress has its own retry loop —
                        # don't burn the lease deadline on it; but an
                        # INFEASIBLE group keeps the fixed deadline so the
                        # actor eventually dies with a diagnostic instead
                        # of pending forever
                        if pg.state != "INFEASIBLE":
                            deadline = time.monotonic() + 120.0
                        await asyncio.sleep(0.25)
                        continue
                    if info.bundle_index >= 0:
                        node_id = pg.bundle_nodes.get(info.bundle_index)
                    else:
                        node_id = next(iter(pg.bundle_nodes.values()), None)
                    node = self.nodes.get(node_id) if node_id else None
                    if node is None or not node.alive:
                        await asyncio.sleep(0.2)
                        continue
                else:
                    node = self._pick_node(info.resources,
                                           strategy=info.strategy,
                                           strategy_node=info.strategy_node,
                                           strategy_soft=info.strategy_soft,
                                           locality=getattr(
                                               info, "locality", None))
                    if node is None:
                        await asyncio.sleep(0.2)  # wait for resources/nodes
                        continue
                # in-flight lease accounting: health-beat load is ~1s
                # stale, so a creation burst would pile onto whichever
                # node looked least loaded at the last beat; counting
                # our own unresolved leases spreads the burst across
                # raylets (parity: GcsActorScheduler's inflight
                # bookkeeping, gcs_actor_scheduler.cc:49).  The charge is
                # held until the actor actually STARTS (actor_started /
                # creation_failed), not merely until the lease RPC
                # returns — a granted-but-still-initializing actor
                # occupies no beat-reported load, so releasing at RPC
                # return erased the spread benefit for bursts larger
                # than the grant-latency window.
                self._charge_actor_lease(info.actor_id, node.node_id)
                try:
                    conn = await self.pool.get(node.raylet_address)
                    reply = await conn.call(
                        "lease_worker_for_actor",
                        {"actor_id": info.actor_id.binary(),
                         "resources": info.resources,
                         "spec_blob": info.creation_spec_blob,
                         "placement_group_id":
                             info.pg_id.binary() if info.pg_id else None,
                         "bundle_index": info.bundle_index,
                         "env_hash": info.env_hash,
                         "env_spawn": info.env_spawn},
                        timeout=60.0,
                    )
                except (rpc.ConnectionLost, rpc.RpcError, OSError,
                        asyncio.TimeoutError) as e:
                    logger.warning("actor lease on %s failed: %s",
                                   node.node_id.hex()[:12], e)
                    self._release_actor_lease_charge(info.actor_id)
                    await asyncio.sleep(0.2)
                    continue
                if not reply.get("granted"):
                    self._release_actor_lease_charge(info.actor_id)
                    await asyncio.sleep(0.1)
                    continue
                await self._settle_actor_grant(info, node, reply)
                return
            self._release_actor_lease_charge(info.actor_id)
            info.state = ACTOR_DEAD
            info.death_cause = "creation timed out: no feasible node"
            self._publish_actor(info)

    def _spawn_schedule_task(self, info: ActorInfo) -> None:
        t = asyncio.get_running_loop().create_task(
            self._schedule_actor(info))
        t.add_done_callback(lambda t: t.exception())

    async def _schedule_actor_batch(self, infos: List[ActorInfo]) -> None:
        """Pipelined bring-up of a registration batch: node selection
        for every actor happens UP FRONT (in-flight lease charges
        applied as assigned, so the spread logic sees its own batch),
        then leases + creation pushes fan out as ONE
        ``lease_workers_for_actors`` RPC per target raylet, all raylets
        in parallel — instead of one awaited round trip per actor.

        Anything the fast path cannot place (gang-bound, no feasible
        node yet, mid-batch failures) falls back to the per-actor
        retry loop ``_schedule_actor``, which owns the 120 s deadline
        and all the slow-path edge cases.
        """
        by_node: Dict[NodeID, List[ActorInfo]] = {}
        for info in infos:
            if info.state in (ACTOR_DEAD, ACTOR_ALIVE):
                continue  # ALIVE: its worker already announced
            if info.pg_id is not None:
                # gang-bound: bundle placement has its own wait loop
                self._spawn_schedule_task(info)
                continue
            node = self._pick_node(
                info.resources, strategy=info.strategy,
                strategy_node=info.strategy_node,
                strategy_soft=info.strategy_soft,
                locality=getattr(info, "locality", None))
            if node is None:
                self._spawn_schedule_task(info)  # waits for capacity
                continue
            self._charge_actor_lease(info.actor_id, node.node_id)
            by_node.setdefault(node.node_id, []).append(info)
        if not by_node:
            return
        await asyncio.gather(*(self._lease_actor_group(node_id, group)
                               for node_id, group in by_node.items()))

    async def _lease_actor_group(self, node_id: NodeID,
                                 group: List[ActorInfo]) -> None:
        """One batched lease+create RPC against one raylet; per-actor
        failures re-enter the single-actor retry loop."""
        node = self.nodes.get(node_id)

        def _fallback(info: ActorInfo) -> None:
            self._release_actor_lease_charge(info.actor_id)
            if info.state != ACTOR_DEAD:
                self._spawn_schedule_task(info)
        if node is None or not node.alive:
            for info in group:
                _fallback(info)
            return
        try:
            conn = await self.pool.get(node.raylet_address)
            reply = await conn.call(
                "lease_workers_for_actors",
                {"actors": [
                    {"actor_id": info.actor_id.binary(),
                     "resources": info.resources,
                     "spec_blob": info.creation_spec_blob,
                     "placement_group_id": None,
                     "bundle_index": -1,
                     "env_hash": info.env_hash,
                     "env_spawn": info.env_spawn}
                    for info in group]},
                timeout=120.0)
            results = {bytes(r["actor_id"]): r
                       for r in (reply or {}).get("results", [])}
        except (rpc.ConnectionLost, rpc.RpcError, OSError,
                asyncio.TimeoutError) as e:
            # OSError included: a raylet that died inside the
            # heartbeat-lag window refuses the CONNECT itself — the
            # whole group must fall back, not strand PENDING with its
            # lease charges leaked
            logger.warning("batched actor lease on %s failed: %s",
                           node_id.hex()[:12], e)
            for info in group:
                _fallback(info)
            return
        for info in group:
            res = results.get(info.actor_id.binary())
            if not res or not res.get("granted"):
                _fallback(info)
                continue
            await self._settle_actor_grant(info, node, res)

    async def _settle_actor_grant(self, info: ActorInfo,
                                  node: "NodeInfo",
                                  reply: Dict[str, Any]) -> None:
        """Post-grant settle shared by the single and batched bring-up
        paths.  Killed while the lease was in flight: don't resurrect
        — reap the leased worker (pg-bound workers are reaped by
        bundle revocation; plain actors need the explicit kill or the
        worker and its resources leak).  Otherwise record placement
        and publish ALIVE, deduped against the worker's own
        ``actor_started`` announcement (usually first)."""
        if info.state == ACTOR_DEAD:
            self._release_actor_lease_charge(info.actor_id)
            try:
                worker_conn = await self.pool.get(
                    tuple(reply["worker_task_address"]))
                worker_conn.push(
                    "kill_actor",
                    {"actor_id": info.actor_id.binary()})
            except Exception:
                pass
            return
        addr = tuple(reply["worker_task_address"])
        if info.state == ACTOR_ALIVE and info.address == addr:
            info.node_id = node.node_id
            return  # actor_started already announced this address
        if info.state == ACTOR_ALIVE and info.address is not None:
            # the actor already has a DIFFERENT living worker (e.g. a
            # pre-restart lease's worker re-announced while a recovery
            # reschedule was in flight): this grant is surplus — reap
            # it, or two processes run the actor and one leaks
            self._release_actor_lease_charge(info.actor_id)
            logger.warning(
                "actor %s: surplus creation grant on %s reaped (already "
                "alive at %s)", info.actor_id.hex()[:12],
                node.node_id.hex()[:12], info.address)
            try:
                worker_conn = await self.pool.get(addr)
                worker_conn.push("kill_actor",
                                 {"actor_id": info.actor_id.binary()})
            except Exception:  # noqa: BLE001 — best-effort reap
                pass
            return
        info.node_id = node.node_id
        info.address = addr
        info.state = ACTOR_ALIVE
        self._publish_actor(info)

    def _charge_actor_lease(self, actor_id: ActorID,
                            node_id: NodeID) -> None:
        self._release_actor_lease_charge(actor_id)  # re-schedule safety
        self._actor_lease_charges[actor_id] = node_id
        self._actor_lease_inflight[node_id] = \
            self._actor_lease_inflight.get(node_id, 0) + 1

    def _release_actor_lease_charge(self, actor_id: ActorID) -> None:
        node_id = self._actor_lease_charges.pop(actor_id, None)
        if node_id is None:
            return
        n_in = self._actor_lease_inflight.get(node_id, 1)
        if n_in <= 1:
            self._actor_lease_inflight.pop(node_id, None)
        else:
            self._actor_lease_inflight[node_id] = n_in - 1

    def _pick_node(self, resources: Dict[str, float],
                   required_node: Optional[NodeID] = None,
                   strategy: str = "DEFAULT",
                   strategy_node: Optional[str] = None,
                   strategy_soft: bool = False,
                   locality: Optional[List[str]] = None
                   ) -> Optional[NodeInfo]:
        """Least-loaded feasible node (actors spread by default); load
        counts this GCS's own unresolved actor leases on top of the
        beat-reported queue so creation bursts fan out immediately.

        ``strategy`` refines the pick: NODE_AFFINITY restricts to the
        named node (``strategy_soft`` falls back to any feasible node
        when it is gone/full), SPREAD ranks by live-actor count so
        sequentially created replicas fan across nodes instead of
        piling onto whichever node's beat-reported load looked lowest
        (equal-load ties broke to the same node every time).

        ``locality``: raylet addresses of nodes already holding the
        creation args' objects (owner-reported).  A DEFAULT-strategy
        pick gives them a soft bonus on the load rank — the creation
        task's arg fetch is then a local arena read instead of a
        cross-node transfer — but load still wins once the holder
        accrues charges, so a burst sharing one arg spreads.
        SPREAD/NODE_AFFINITY ignore the hint: an explicit placement
        intent beats a data-locality preference."""
        if strategy == "NODE_AFFINITY" and strategy_node and \
                required_node is None:
            try:
                required_node = NodeID(bytes.fromhex(strategy_node))
            except ValueError:
                logger.warning("NODE_AFFINITY node id %r is not valid "
                               "hex", strategy_node)
                if not strategy_soft:
                    # a HARD pin must never silently land elsewhere:
                    # stay pending (creation times out with a
                    # diagnostic) rather than violate the pin
                    return None
                required_node = None
        candidates = []
        for node in self.nodes.values():
            if not node.alive or node.state != NODE_ACTIVE:
                # DRAINING/DRAINED nodes finish what they hold but take
                # no new placements — even a hard NODE_AFFINITY pin
                # pends (the drain either completes or aborts shortly)
                continue
            if node.max_workers == 0 and required_node is None:
                # dedicated control node (e.g. a 0-CPU HA head): it can
                # never spawn a worker, so even a 0-resource actor
                # would pend there forever
                continue
            if required_node is not None and node.node_id != required_node:
                continue
            if all(node.resources_available.get(k, 0.0) >= v
                   for k, v in resources.items()):
                candidates.append(node)
        if not candidates:
            if required_node is not None and strategy_soft:
                return self._pick_node(resources)
            return None
        loc: set = set()
        if locality and strategy == "DEFAULT":
            # owner-reported raylet addresses of nodes holding the
            # creation args: a SOFT tie-break bonus on the load rank,
            # never a hard filter — a whole burst sharing one plasma
            # arg must still spread once the holder accrues charges
            # (a hard narrow collapsed fleets onto the arg's node)
            loc = {tuple(a) for a in locality
                   if isinstance(a, (list, tuple))}
        if strategy == "SPREAD":
            per_node: Dict[NodeID, int] = {}
            for other in self.actors.values():
                if other.state == ACTOR_ALIVE and other.node_id is not None:
                    per_node[other.node_id] = \
                        per_node.get(other.node_id, 0) + 1
            return min(candidates, key=lambda n: (
                per_node.get(n.node_id, 0)
                + self._actor_lease_inflight.get(n.node_id, 0),
                n.load))
        return min(candidates,
                   key=lambda n: n.load + self._actor_lease_inflight.get(
                       n.node_id, 0)
                   - (1 if tuple(n.raylet_address) in loc else 0))

    async def handle_actor_started(self, conn, data):
        """The actor worker reports in after executing its creation task."""
        actor_id = ActorID(data["actor_id"])
        conn.context["actor_id"] = actor_id
        self._release_actor_lease_charge(actor_id)
        info = self.actors.get(actor_id)
        if info is None:
            return False
        if info.state == ACTOR_DEAD:
            return False  # killed while starting (e.g. pg removed)
        info.address = tuple(data["task_address"])
        info.state = ACTOR_ALIVE
        self._publish_actor(info)
        await self._wal_flush()
        return True

    async def handle_actor_creation_failed(self, conn, data):
        actor_id = ActorID(data["actor_id"])
        self._on_actor_worker_lost(actor_id, data.get("reason", "creation failed"),
                                   allow_restart=False)
        await self._wal_flush()
        return True

    async def handle_get_actor(self, conn, data):
        if "name" in data:
            key = (data.get("namespace", "default"), data["name"])
            actor_id = self.named_actors.get(key)
            if actor_id is None:
                return None
        else:
            actor_id = ActorID(data["actor_id"])
        info = self.actors.get(actor_id)
        if info is None:
            return None
        msg = self._actor_message(info)
        msg["class_name"] = info.class_name
        msg["name"] = info.name
        return msg

    async def handle_list_actors(self, conn, data):
        return [dict(self._actor_message(a), name=a.name,
                     class_name=a.class_name)
                for a in self.actors.values()]

    async def handle_kill_actor(self, conn, data):
        actor_id = ActorID(data["actor_id"])
        info = self.actors.get(actor_id)
        if info is None:
            return False
        info.max_restarts = 0  # no_restart semantics
        if info.address is not None:
            try:
                worker_conn = await self.pool.get(info.address)
                worker_conn.push("kill_actor", {"actor_id": actor_id.binary()})
            except Exception:
                pass
        self._on_actor_worker_lost(actor_id, "killed via kill_actor",
                                   allow_restart=False)
        await self._wal_flush()  # an acked kill must not resurrect
        return True

    def _on_actor_worker_lost(self, actor_id: ActorID, reason: str,
                              allow_restart: bool = True) -> None:
        self._release_actor_lease_charge(actor_id)
        info = self.actors.get(actor_id)
        if info is None or info.state == ACTOR_DEAD:
            return
        # incident journal: an actor worker lost to a crash is a death
        # episode whether or not a restart saves it (the shipped flight
        # tail of the dead worker merges into the same incident)
        try:
            self._open_or_merge_incident(
                "death",
                f"actor {actor_id.hex()[:12]} "
                f"({info.class_name or 'unknown'}) worker lost: "
                f"{reason}",
                job=info.owner_job.hex() if info.owner_job else None)
        except Exception:  # noqa: BLE001 — never wedge the death path
            logger.exception("incident open failed for actor death")
        if allow_restart and info.num_restarts < info.max_restarts:
            info.num_restarts += 1
            info.state = ACTOR_RESTARTING
            info.address = None
            info.node_id = None
            self._publish_actor(info)
            logger.info("restarting actor %s (%d/%d): %s",
                        actor_id.hex()[:12], info.num_restarts,
                        info.max_restarts, reason)
            self._emit_event(
                "WARNING", "ACTOR_RESTARTING",
                f"actor {actor_id.hex()[:12]} restarting "
                f"({info.num_restarts}/{info.max_restarts}): {reason}",
                actor_id=actor_id.hex(), class_name=info.class_name)
            asyncio.get_running_loop().create_task(self._schedule_actor(info))
        else:
            info.state = ACTOR_DEAD
            info.death_cause = reason
            info.address = None
            self._emit_event(
                "ERROR", "ACTOR_DEAD",
                f"actor {actor_id.hex()[:12]} dead: {reason}",
                actor_id=actor_id.hex(), class_name=info.class_name)
            self._publish_actor(info)
            if info.name is not None:
                self.named_actors.pop((info.namespace, info.name), None)

    # ------------------------------------------------------------------
    # placement groups (GcsPlacementGroupManager/Scheduler, 2-phase)
    # ------------------------------------------------------------------
    async def handle_create_placement_group(self, conn, data):
        pg = PlacementGroupInfo(
            pg_id=PlacementGroupID(data["pg_id"]),
            bundles=[dict(b) for b in data["bundles"]],
            strategy=data.get("strategy", "PACK"),
            name=data.get("name"),
        )
        self.placement_groups[pg.pg_id] = pg
        self._wal_pg(pg)
        await self._schedule_pg(pg)
        self._schedule_persist()
        await self._wal_flush()
        return {"state": pg.state}

    async def handle_placement_group_ready(self, conn, data):
        """Current PG state; with ``block_s`` > 0, long-poll: the reply
        is held until the group reaches CREATED/REMOVED (or the block
        window closes).  One RPC replaces the client-side sleep loop
        whose fixed poll interval quantized create+wait latency."""
        pg_id = PlacementGroupID(data["pg_id"])
        pg = self.placement_groups.get(pg_id)
        if pg is None:
            return {"state": "REMOVED"}
        block_s = float(data.get("block_s") or 0.0)
        if block_s > 0:
            loop = asyncio.get_running_loop()
            deadline = loop.time() + min(block_s, 30.0)
            while pg.state not in ("CREATED", "REMOVED"):
                remaining = deadline - loop.time()
                if remaining <= 0:
                    break
                ev = self._pg_waiters.setdefault(pg_id, asyncio.Event())
                try:
                    await asyncio.wait_for(ev.wait(), remaining)
                except asyncio.TimeoutError:
                    break
        return {"state": pg.state,
                "bundle_nodes": {i: n.binary()
                                 for i, n in pg.bundle_nodes.items()}}

    async def handle_list_placement_groups(self, conn, data):
        return [
            {"pg_id": pg.pg_id.binary(), "state": pg.state,
             "strategy": pg.strategy, "bundles": pg.bundles,
             "name": pg.name,
             "bundle_nodes": {i: n.binary()
                              for i, n in pg.bundle_nodes.items()}}
            for pg in self.placement_groups.values()
        ]

    async def handle_remove_placement_group(self, conn, data):
        pg = self.placement_groups.get(PlacementGroupID(data["pg_id"]))
        if pg is None:
            return False
        # terminal state BEFORE any await so concurrent _schedule_actor /
        # _schedule_pg loops observe REMOVED and cannot re-lease against
        # the group while bundles are being returned
        pg.state = "REMOVED"
        self._wake_pg_waiters(pg.pg_id)
        targets = [(i, self.nodes.get(n)) for i, n in pg.bundle_nodes.items()]
        pg.bundle_nodes.clear()
        # actors gang-bound to the group die with it, through the common
        # death path (clears named_actors; never restarts); their worker
        # processes are killed by the raylets' return_bundle path
        for info in list(self.actors.values()):
            if info.pg_id == pg.pg_id and info.state != ACTOR_DEAD:
                self._on_actor_worker_lost(info.actor_id,
                                           "placement group removed",
                                           allow_restart=False)
        await self._return_bundles(pg, targets)
        self.publish(f"pg:{pg.pg_id.hex()}", {"state": "REMOVED"})
        self._wal_pg(pg)
        self._schedule_persist()
        await self._wal_flush()
        return True

    async def _pg_retry_loop(self) -> None:
        """Reschedule unplaced groups as resources free up.

        Parity: GcsPlacementGroupManager's pending queue + retry on
        resource change (gcs_placement_group_manager.h:221) — raylet
        resource views are refreshed by health reports, so a group that
        failed placement (e.g. a previous gang's resources not yet
        returned) becomes placeable moments later.
        """
        while True:
            await asyncio.sleep(0.25)
            now = time.monotonic()
            for pg in list(self.placement_groups.values()):
                if pg.state not in ("PENDING", "INFEASIBLE", "RESCHEDULING"):
                    continue
                if now < pg.retry_at:
                    continue
                try:
                    await self._schedule_pg(pg)
                except Exception:
                    logger.exception("pg retry failed %s",
                                     pg.pg_id.hex()[:12])
                if pg.state == "CREATED":
                    pg.retry_backoff = 0.5
                else:  # back off while unplaceable (cap: 5s)
                    pg.retry_at = now + pg.retry_backoff
                    pg.retry_backoff = min(pg.retry_backoff * 2, 5.0)

    async def _schedule_pg(self, pg: PlacementGroupInfo) -> None:
        """Pick nodes per strategy, then two-phase prepare/commit bundles.

        Parity: GcsPlacementGroupScheduler (gcs_placement_group_scheduler.h:265).
        """
        if pg.scheduling or pg.state in ("CREATED", "REMOVED"):
            return
        pg.scheduling = True
        try:
            await self._schedule_pg_inner(pg)
        finally:
            pg.scheduling = False

    def _set_pg_state(self, pg: PlacementGroupInfo, state: str) -> None:
        """Transition + publish, but only on an actual change (the retry
        loop would otherwise re-publish the same state twice a second)."""
        if pg.state == state:
            return
        pg.state = state
        self._wake_pg_waiters(pg.pg_id)
        self.publish(f"pg:{pg.pg_id.hex()}", {"state": state})
        self._wal_pg(pg)
        self._schedule_persist()

    def _wake_pg_waiters(self, pg_id: PlacementGroupID) -> None:
        ev = self._pg_waiters.pop(pg_id, None)
        if ev is not None:
            ev.set()

    async def _return_bundles(self, pg: PlacementGroupInfo,
                              targets: List[Tuple[int, "NodeInfo"]]) -> None:
        """Best-effort return_bundle for each (index, node); dead or
        unreachable raylets drop their reservations when they go away."""
        for index, node in targets:
            if node is None or not node.alive:
                continue
            try:
                conn = await self.pool.get(node.raylet_address)
                await conn.call("return_bundle",
                                {"pg_id": pg.pg_id.binary(),
                                 "bundle_index": index}, timeout=30.0)
            except Exception:
                pass

    async def _schedule_pg_inner(self, pg: PlacementGroupInfo) -> None:
        # a RESCHEDULING group may still hold bundles on surviving nodes
        # from its previous placement; release them before re-planning so
        # they neither block the new plan nor leak when it lands elsewhere
        if pg.bundle_nodes:
            await self._release_pg_bundles(pg, set(pg.bundle_nodes))
            pg.bundle_nodes.clear()
        placement = self._plan_bundles(pg)
        if placement is None:
            self._set_pg_state(pg, "INFEASIBLE")
            return
        # phase 1: prepare on every involved raylet
        prepared: List[int] = []
        ok = True
        for index, node in placement.items():
            try:
                conn = await self.pool.get(node.raylet_address)
                granted = await conn.call(
                    "prepare_bundle",
                    {"pg_id": pg.pg_id.binary(), "bundle_index": index,
                     "resources": pg.bundles[index]}, timeout=30.0)
                if granted:
                    prepared.append(index)
                else:
                    ok = False
                    break
            except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError):
                # the raylet may have reserved before the reply was lost —
                # include it in the rollback so the reservation can't leak
                prepared.append(index)
                ok = False
                break
        if ok and pg.state != "REMOVED":
            # phase 2: commit
            try:
                for index, node in placement.items():
                    conn = await self.pool.get(node.raylet_address)
                    committed = await conn.call(
                        "commit_bundle",
                        {"pg_id": pg.pg_id.binary(),
                         "bundle_index": index}, timeout=30.0)
                    if not committed:
                        # raylet lost the bundle (e.g. restarted between
                        # prepare and commit) — replan from scratch
                        ok = False
                        break
                    pg.bundle_nodes[index] = node.node_id
            except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError):
                ok = False
        if not ok or pg.state == "REMOVED":
            # roll back every prepared reservation (committed indices are
            # always a subset — bundle_nodes was cleared at entry)
            await self._return_bundles(
                pg, [(i, placement[i]) for i in sorted(prepared)])
            pg.bundle_nodes.clear()
            if pg.state != "REMOVED":  # removal is terminal — don't resurrect
                self._set_pg_state(pg, "PENDING")
            return
        pg.state = "CREATED"
        self._wake_pg_waiters(pg.pg_id)
        self.publish(f"pg:{pg.pg_id.hex()}",
                     {"state": pg.state,
                      "bundle_nodes": {i: n.binary()
                                       for i, n in pg.bundle_nodes.items()}})
        self._wal_pg(pg)
        self._schedule_persist()

    def _plan_bundles(self, pg: PlacementGroupInfo
                      ) -> Optional[Dict[int, NodeInfo]]:
        """Bundle→node assignment per strategy, slice/topology aware.

        PACK prefers one node (and one TPU slice); SPREAD round-robins;
        STRICT_* are the hard variants (parity:
        policy/bundle_scheduling_policy.cc).  Nodes in the same TPU slice
        sort adjacently so PACKed gangs land on one ICI domain.
        """
        alive = [n for n in self.nodes.values()
                 if n.alive and n.state == NODE_ACTIVE]
        if not alive:
            return None
        alive.sort(key=lambda n: (n.topology.get("slice", ""),
                                  n.topology.get("worker_index", 0)))
        try:
            # native bundle placement (src/sched_core.cc — parity with
            # the reference's C++ bundle_scheduling_policy.cc); node
            # order above keeps same-slice nodes adjacent for PACK
            from ray_tpu.core import native

            assignment = native.sched_place_bundles(
                [n.resources_available for n in alive], pg.bundles,
                pg.strategy)
            if assignment is None:
                return None
            return {i: alive[idx] for i, idx in enumerate(assignment)}
        except OSError:  # toolchain unavailable: python fallback
            pass
        avail = {n.node_id: dict(n.resources_available) for n in alive}

        def fits(node: NodeInfo, bundle: Dict[str, float]) -> bool:
            a = avail[node.node_id]
            return all(a.get(k, 0.0) >= v for k, v in bundle.items())

        def take(node: NodeInfo, bundle: Dict[str, float]) -> None:
            a = avail[node.node_id]
            for k, v in bundle.items():
                a[k] = a.get(k, 0.0) - v

        placement: Dict[int, NodeInfo] = {}
        if pg.strategy in ("PACK", "STRICT_PACK"):
            # try to fit everything on a single node first
            for node in alive:
                trial = dict(avail[node.node_id])
                all_fit = True
                for bundle in pg.bundles:
                    if all(trial.get(k, 0.0) >= v for k, v in bundle.items()):
                        for k, v in bundle.items():
                            trial[k] = trial.get(k, 0.0) - v
                    else:
                        all_fit = False
                        break
                if all_fit:
                    for i, bundle in enumerate(pg.bundles):
                        placement[i] = node
                        take(node, bundle)
                    return placement
            if pg.strategy == "STRICT_PACK":
                return None
            # soft pack: greedy fill node by node
            for i, bundle in enumerate(pg.bundles):
                node = next((n for n in alive if fits(n, bundle)), None)
                if node is None:
                    return None
                placement[i] = node
                take(node, bundle)
            return placement
        else:  # SPREAD / STRICT_SPREAD
            used_nodes: set = set()
            for i, bundle in enumerate(pg.bundles):
                fresh = [n for n in alive
                         if n.node_id not in used_nodes and fits(n, bundle)]
                if fresh:
                    node = fresh[0]
                elif pg.strategy == "STRICT_SPREAD":
                    return None
                else:
                    node = next((n for n in alive if fits(n, bundle)), None)
                    if node is None:
                        return None
                placement[i] = node
                used_nodes.add(node.node_id)
                take(node, bundle)
            return placement

    async def _release_pg_bundles(self, pg: PlacementGroupInfo,
                                  indices: set) -> None:
        node_of = lambda i: self.nodes.get(pg.bundle_nodes[i]) \
            if pg.bundle_nodes.get(i) else None
        await self._return_bundles(pg, [(i, node_of(i)) for i in indices])
