"""Runtime configuration flag table.

Parity with the reference's ``RAY_CONFIG(type, name, default)`` macro table
(reference ``src/ray/common/ray_config_def.h``): a single flat registry of
typed flags, each overridable by an ``RAY_TPU_<NAME>`` environment variable
or via ``ray_tpu.init(_system_config={...})``.  The resolved table is
serialized from the head node to every other process so the whole cluster
sees one consistent configuration.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field, fields
from typing import Any, Dict

_ENV_PREFIX = "RAY_TPU_"


@dataclass
class Config:
    # ---- memory monitor (reference memory_monitor.h:52 +
    # worker_killing_policy.h:30) -----------------------------------------
    #: host memory-used fraction above which the raylet kills a retriable
    #: task worker instead of risking the OS OOM killer (0 disables)
    memory_usage_threshold: float = 0.95
    #: how often the monitor samples /proc/meminfo (ms; 0 disables)
    memory_monitor_refresh_ms: int = 250

    # ---- object store ----------------------------------------------------
    #: Bytes of shared memory for the per-node object store (0 = auto: 30%
    #: of system memory, capped).
    object_store_memory: int = 0
    #: Objects at or below this size are kept in the owner's in-process
    #: memory store and inlined into task specs instead of going to shm.
    max_direct_call_object_size: int = 100 * 1024
    #: Chunk size for node-to-node object transfer.
    object_transfer_chunk_size: int = 5 * 1024 * 1024
    #: In-flight chunk requests per transfer source (pipelining depth of
    #: a pull; 1 = the old serial request/reply protocol).
    object_transfer_window: int = 8
    #: Max holders a single pull stripes chunks across (sources beyond
    #: this are kept as failover spares).
    object_transfer_max_sources: int = 4
    #: Register in-progress pulls as *partial* locations with the owner
    #: so concurrent pullers chain off each other (1->N broadcasts
    #: self-organize into a tree instead of N pulls hammering the one
    #: sealed holder).
    object_transfer_partial_locations: bool = True
    #: Per-chunk request timeout; also bounds how long a chunk request
    #: against a partial (in-progress) holder waits for that holder's
    #: own transfer to produce the chunk.
    object_transfer_chunk_timeout_s: float = 30.0
    #: When the holder's arena file is visible on this host (multiple
    #: raylets per machine — virtual clusters, multi-node tests), copy
    #: arena-to-arena through shared memory instead of the TCP stack
    #: (the reference runs ONE plasma store per host for this reason;
    #: the pin/lease protocol still runs over RPC).
    object_transfer_shm_fastpath: bool = True
    #: Fraction of store capacity at which LRU eviction starts.
    object_store_eviction_fraction: float = 1.0
    #: Directory for spilled objects ("" = <session_dir>/spill).
    object_spilling_directory: str = ""
    #: External spill tier as a URI (e.g. ``file:///mnt/shared/spill``;
    #: scheme-pluggable via ``ray_tpu.air.storage.register_storage`` —
    #: parity: reference ``_private/external_storage.py`` smart_open
    #: URIs).  When set, spilled primaries go to the URI and the OWNER
    #: records it, so the object survives the spilling node's death and
    #: restores on any node.  "" = local-directory spill only.
    object_spilling_uri: str = ""
    #: Start spilling primary copies when the store is this full.
    #: Deprecated alias of ``object_spill_threshold`` (kept for older
    #: configs; the new name wins when both are set).
    object_spilling_threshold: float = 0.8
    #: Canonical spill-pressure knob: arena-used fraction above which
    #: the raylet spills cold sealed primaries to the disk tier
    #: (LRU by last pin; pinned/unsealed copies never spill).
    #: < 0 = inherit ``object_spilling_threshold``.
    object_spill_threshold: float = -1.0
    #: Cap on bytes resident in the local spill tier (0 = unbounded).
    #: At the cap the raylet stops spilling; creates then fail with
    #: ObjectStoreFullError once eviction is also exhausted.
    object_spill_max_bytes: int = 0
    #: Metadata lock-stripe shards in the native store (0 = library
    #: default, 16).  More shards = less create/seal/get contention
    #: between concurrent writers, at a small cross-shard sweep cost
    #: for stats/eviction scans.
    store_metadata_shards: int = 16
    #: Async spill-AHEAD watermark (arena-used fraction): above it the
    #: raylet's background tick spills cold sealed primaries toward the
    #: watermark OFF the create path, so a streaming shuffle (or any
    #: bursty producer) doesn't pay spill latency inside ``put()`` when
    #: pressure later crosses ``object_spill_threshold``.  0 disables
    #: (spilling then happens only reactively, on the create path).
    object_spill_ahead_watermark: float = 0.0

    # ---- scheduling ------------------------------------------------------
    #: Hybrid policy: pack onto the local/first node until its utilization
    #: exceeds this threshold, then spread (reference
    #: ``hybrid_scheduling_policy.h:48``).
    scheduler_spread_threshold: float = 0.5
    #: Max tasks in flight to a single leased worker before requesting more
    #: workers (pipelining depth).
    max_tasks_in_flight_per_worker: int = 64
    #: Tasks per push RPC frame.  Smaller chunks stream completions back
    #: while the worker executes the next chunk; one cap-sized frame would
    #: serialize driver and worker into lock-step.
    task_push_chunk_size: int = 16
    #: Seconds a leased idle worker is kept before being returned.
    idle_worker_lease_timeout_s: float = 0.25
    #: Number of workers each raylet keeps pre-started.
    #: workers to warm up at raylet start; -1 = auto (min(4, num CPUs)),
    #: parity: reference ``prestart_worker_first_driver``
    num_prestart_workers: int = -1
    #: Hard cap on workers a raylet will spawn (0 = 4 * num_cpus).
    max_workers_per_node: int = 0
    #: Coalesce concurrent driver-side actor registrations into one
    #: ``register_actor_batch`` RPC (idempotent, keyed on actor_id).
    #: Off: one ``register_actor`` round trip per creation.
    actor_register_batch: bool = True
    #: Cap on actors per registration-batch RPC frame.
    actor_register_batch_max: int = 256
    #: Owner-side lease cache: park an idling leased worker keyed by
    #: (raylet, resource shape, runtime-env hash) through its idle grace
    #: so the next compatible scheduling key claims it WITHOUT a raylet
    #: round trip (parity: reference lease reuse in
    #: direct_task_transport).  Off: leases stay private to the
    #: scheduling key that acquired them.
    lease_cache_enabled: bool = True
    #: Max workers parked in the owner-side lease cache at once; beyond
    #: it an idling lease returns to the raylet immediately.
    lease_cache_size: int = 32
    #: Background warm-pool rebuild rate (spawns per 0.2 s reap tick,
    #: per raylet) toward the demand-driven pool target while the lease
    #: plane is quiet — the next actor wave then lands on warm forks.
    warm_pool_rebuild_per_tick: int = 4
    #: Owner-side locality lease routing (parity: the reference's
    #: LocalityAwareLeasePolicy): a DEFAULT-strategy task whose plasma
    #: args are known to live on another node sends its FIRST lease
    #: request to that node's raylet, so the task runs next to its data
    #: (the streaming data plane's map tasks depend on this).  Soft:
    #: the target can still spill the lease back; an unreachable target
    #: falls back to the local route.
    task_locality_enabled: bool = True

    # ---- fault tolerance -------------------------------------------------
    #: GCS table persistence backend: "" / "file" = session-dir pickle,
    #: "memory" = ephemeral, or an air.storage URI (e.g. file:///nfs/gcs)
    #: that survives losing the head host (parity: the reference's
    #: gcs_table_storage over Redis / in-memory store clients)
    gcs_table_storage: str = ""
    #: Write-ahead log in front of the GCS table snapshot: table-
    #: mutating handlers append a typed record and the reply is held
    #: until the record is durable, so an acked mutation survives an
    #: immediate head SIGKILL (the debounced snapshot alone loses the
    #: debounce window).  Off: snapshot-only persistence (old behavior).
    gcs_wal_enabled: bool = True
    #: WAL durability policy: "fsync" = group-commit fsync before the
    #: ack (survives host power loss); "write" = write(2) only (page
    #: cache: survives process SIGKILL, cheaper on real disks).
    gcs_wal_sync: str = "fsync"
    #: Compact (fold the WAL into the snapshot + truncate) when the log
    #: exceeds this many bytes, on top of the debounced snapshot cycle.
    gcs_wal_compact_bytes: int = 8 * 1024 * 1024
    #: Debounce window of the whole-table snapshot while the WAL is
    #: healthy (the WAL carries ack durability, so the snapshot is just
    #: the compaction base).  With the WAL off/degraded the GCS falls
    #: back to a tight 0.2 s debounce.
    gcs_snapshot_debounce_s: float = 2.0
    #: How long drivers (and actor workers) keep retrying to reconnect
    #: after the GCS/head dies before giving up (0 disables reconnect).
    gcs_client_reconnect_timeout_s: float = 60.0
    #: First-retry delay of the GCS reconnect loops (worker
    #: ``_reconnect_head``, raylet ``_try_gcs_reconnect``); grows
    #: exponentially with full jitter so a fleet-wide head restart
    #: doesn't stampede re-registration in lock-step.
    gcs_reconnect_backoff_base_s: float = 0.2
    #: Cap on the reconnect backoff delay.
    gcs_reconnect_backoff_max_s: float = 5.0
    default_max_task_retries: int = 3
    default_max_actor_restarts: int = 0
    #: Period of raylet -> GCS health reports.
    health_report_period_s: float = 1.0
    #: GCS declares a node dead after this long without a report.
    health_timeout_s: float = 10.0
    #: Wall-clock budget for one graceful node drain (the raylet-side
    #: object/spill migration leg).  0 disables the graceful protocol:
    #: drain_node falls back to immediate removal (pre-autoscaler
    #: semantics, used by crash-simulation tests).
    drain_timeout_s: float = 60.0
    #: Max attempts to reconstruct a lost object through lineage.
    max_lineage_reconstruction_depth: int = 100

    # ---- RPC / transport -------------------------------------------------
    rpc_connect_timeout_s: float = 10.0
    #: Base delay for the first RPC retry (grows exponentially).
    rpc_retry_delay_s: float = 0.1
    #: Attempts per retried call chain, counting the first try.
    rpc_max_retries: int = 5
    #: Cap on the exponential backoff between attempts.
    rpc_backoff_max_s: float = 5.0
    #: Backoff growth factor per retry.
    rpc_backoff_multiplier: float = 2.0
    #: ± fraction of jitter applied to every backoff delay (decorrelates
    #: retry storms after a node/GCS blip).
    rpc_backoff_jitter: float = 0.2
    #: Total wall-clock budget for one retried call chain, across all
    #: attempts and backoffs (0 disables the budget).  Per-attempt
    #: timeouts shrink to the remaining budget.
    rpc_call_deadline_s: float = 30.0
    #: Long-poll pubsub batch window.
    pubsub_batch_window_s: float = 0.01

    # ---- workers ---------------------------------------------------------
    worker_register_timeout_s: float = 30.0
    #: Seconds between raylet resource-view broadcasts to the GCS (the
    #: ray_syncer-equivalent cadence).
    resource_broadcast_period_s: float = 0.1

    # ---- TPU / mesh ------------------------------------------------------
    #: Default logical mesh axis names, outermost first.
    mesh_axis_order: str = "dp,fsdp,sp,tp"
    #: Label under which TPU chips appear as a schedulable resource.
    tpu_resource_name: str = "TPU"

    # ---- misc ------------------------------------------------------------
    session_root: str = "/tmp/ray_tpu"
    log_to_driver: bool = True
    event_stats: bool = True
    task_events_buffer_size: int = 10000

    # ---- telemetry -------------------------------------------------------
    #: Period of the per-process metrics/span flush to the GCS (worker,
    #: raylet, and GCS-local loops all use it).
    metrics_report_period_s: float = 5.0
    #: Master switch for the runtime ``ray_tpu_*`` producers and span
    #: recording (user-defined metrics still flush when off).
    metrics_enabled: bool = True
    #: Per-process cap on live tagsets per metric; new tagsets beyond it
    #: are dropped with one warning (guards against unbounded tag values).
    metrics_max_tagsets: int = 64
    #: Per-process buffer of timeline spans awaiting flush (oldest drop).
    telemetry_spans_buffer_size: int = 4096
    #: GCS-side ring of transfer/RPC spans served to ``timeline()``.
    telemetry_spans_table_size: int = 20000

    # ---- metrics history + alerting (core/metrics_history.py) ------------
    #: Period of the GCS history sampler: each tick folds the merged
    #: metrics table into per-series ring buffers (counters as deltas)
    #: and re-evaluates recording + alert rules.
    metrics_history_interval_s: float = 2.0
    #: History retention window.  Ring capacity per series is
    #: ``window / interval`` points — the memory bound is
    #: ``series x capacity`` points, evictions are counted
    #: (``ray_tpu_metrics_history_evicted_total``).
    metrics_history_window_s: float = 300.0
    #: Master switch for the history/alert plane (the GCS loop is a
    #: no-op when off; ``/api/timeseries`` and ``ray-tpu alerts`` then
    #: serve empty views).
    metrics_history_enabled: bool = True
    #: Error budget of the serve SLO burn-rate alert: the fraction of
    #: requests allowed over ``serve_slo_latency_s``.  Burn rate =
    #: observed miss fraction / budget; the built-in rule fires when it
    #: sustains above 1.0.
    serve_slo_error_budget: float = 0.01

    # ---- distributed tracing (core/tracing.py) ---------------------------
    #: Master switch for the native request-scoped tracing plane.  Off:
    #: no trace context is ever born, every hop short-circuits on its
    #: absence — the hot path pays nothing.
    tracing_enabled: bool = True
    #: Tail-sampling retention for FAST SUCCESSFUL traces, decided at
    #: trace completion in the GCS (errors, sheds, deadline misses,
    #: retried and SLO-violating traces are always kept).
    trace_sample_keep_fraction: float = 0.05
    #: GCS-side cap on traces held (assembling + retained); oldest
    #: evict with accounting (``ray_tpu_trace_evicted_total``).
    trace_table_size: int = 2000
    #: Serve latency SLO (seconds): a request slower than this is
    #: tagged ``slo_miss`` on its root span and always retained by tail
    #: sampling (0 disables; errors/sheds are always retained anyway).
    serve_slo_latency_s: float = 0.0

    # ---- serving plane (serve/) ------------------------------------------
    #: Per-deployment backlog cap at the ingress proxy (queued + in
    #: flight); beyond it requests shed with 429 (0 = unbounded, i.e.
    #: shedding off — overload then collapses into queueing delay).
    serve_proxy_queue_limit: int = 128
    #: ``Retry-After`` seconds attached to shed (429) responses.
    serve_shed_retry_after_s: float = 1.0
    #: Default per-request deadline when the client sends none.
    serve_request_deadline_s: float = 60.0
    #: Sustained-signal delay before the autoscaler adds replicas.
    serve_autoscale_upscale_delay_s: float = 0.3
    #: Sustained-signal delay before it removes replicas (hysteresis:
    #: much longer than upscale so brief lulls don't thrash).
    serve_autoscale_downscale_delay_s: float = 2.0
    #: One bounded wait for ALL replica metric probes per reconcile
    #: tick (replaces the old serial per-replica 5 s timeouts).
    serve_metrics_timeout_s: float = 2.0
    #: Attempts for a serve request whose replica died mid-flight
    #: (router re-assigns to a healthy replica between attempts).
    serve_request_retries: int = 3
    #: Gang bring-up budget for a sharded (num_shards > 1) replica: all
    #: shards of the gang must report ready within this window or the
    #: whole gang is killed and retried (all-or-nothing readiness).
    serve_gang_ready_timeout_s: float = 120.0
    #: Route KV pages to the plasma (arena) path regardless of size —
    #: paged KV must live in the shared arena to survive replica
    #: migration and ride the spill tier.  False = place by size like
    #: any other object (small pages then stay in the owner's
    #: in-process store).
    serve_kv_pages_in_arena: bool = True
    #: Default page-table budget per replica (pages); a request whose
    #: page demand would exceed it stays queued until eviction frees
    #: pages.  Overridable per deployment via batching.kv_max_pages.
    serve_kv_max_pages: int = 4096

    # ---- head supervision (core/supervisor.py) ---------------------------
    #: Driver-side monitor for an init()-owned head: when the head
    #: process (GCS + head raylet) dies unexpectedly, respawn it on the
    #: same GCS port and session dir so the PR-11 recovery path
    #: (snapshot+WAL replay, client reconnect backoff) takes over.
    #: Previously only the test harness performed this restart.
    gcs_auto_respawn: bool = True
    #: Max automatic head respawns per driver session (a crash-looping
    #: GCS must not burn the host forever); 0 = unlimited.
    gcs_respawn_max: int = 3

    # ---- continuous profiling (core/profiler.py) -------------------------
    #: Start every process's sampling profiler at boot (always-on mode).
    #: Off by default: the runtime pays ZERO profiling cost unless this
    #: is set or ``ray-tpu profile`` arms the cluster at runtime.
    profiler_enabled: bool = False
    #: Stack samples per second while profiling is active.
    profiler_hz: float = 25.0
    #: Per-process cap on distinct (task, stack) fold keys between
    #: flushes; overflow samples are counted, not stored.
    profiler_max_stacks: int = 2000
    #: GCS-side ring of profile records served by ``get_profile``.
    profiler_table_size: int = 50000

    # ---- incident forensics (core/flight_recorder.py) --------------------
    #: Every process keeps a crash-surviving mmap ring of its recent
    #: state transitions (docs/observability.md "Incidents and
    #: postmortems").  Off: ``flight_recorder.record`` is a single
    #: None test — the hot path pays nothing.
    flight_recorder_enabled: bool = True
    #: Per-process ring file size in bytes (256 B/frame → 1024 frames
    #: at the default; the whole file is the crash-loss bound).
    flight_ring_bytes: int = 262144
    #: GCS-side cap on retained incidents (oldest evicted; incidents
    #: persist via the WAL so the cap also bounds snapshot growth).
    incident_table_size: int = 200
    #: Deaths/alert-firings within this window of an open incident's
    #: last update merge into it instead of opening a new one (a gang
    #: death is one incident, not N).
    incident_window_s: float = 120.0
    #: Per-severity capacity of the GCS cluster-event retention rings
    #: (evictions counted in ``ray_tpu_events_evicted_total``).
    event_ring_size: int = 5000

    def apply_env_overrides(self) -> "Config":
        for f in fields(self):
            env = os.environ.get(_ENV_PREFIX + f.name.upper())
            if env is None:
                continue
            if f.type in ("int", int):
                setattr(self, f.name, int(env))
            elif f.type in ("float", float):
                setattr(self, f.name, float(env))
            elif f.type in ("bool", bool):
                setattr(self, f.name, env.lower() in ("1", "true", "yes"))
            else:
                setattr(self, f.name, env)
        return self

    def apply_overrides(self, overrides: Dict[str, Any] | None) -> "Config":
        for key, value in (overrides or {}).items():
            if not hasattr(self, key):
                raise ValueError(f"Unknown system config key: {key!r}")
            setattr(self, key, value)
        return self

    def to_json(self) -> str:
        return json.dumps({f.name: getattr(self, f.name) for f in fields(self)})

    @classmethod
    def from_json(cls, blob: str) -> "Config":
        return cls(**json.loads(blob))


_global_config: Config | None = None


def get_config() -> Config:
    global _global_config
    if _global_config is None:
        _global_config = Config().apply_env_overrides()
    return _global_config


def set_config(config: Config) -> None:
    global _global_config
    _global_config = config
