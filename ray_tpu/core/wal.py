"""Write-ahead log for the GCS control-plane tables.

Parity: the reference makes GCS storage pluggable (Redis-backed
``gcs_table_storage``) so an acked mutation survives a head restart.
Here the durable tier is a whole-table snapshot behind ``TableStorage``
(``core/table_storage.py``) written on a *debounced* timer — so any
mutation acked inside the debounce window used to be silently lost on
SIGKILL.  This module closes that window: table-mutating GCS handlers
append a typed record to a local append-log *before replying*, and a
restarted GCS replays ``snapshot + log`` to the exact acked state.

Design:

* **Framing** — an 8-byte file header, then length-prefixed records::

      [u32 length][u32 crc32(payload)][payload]

  ``payload = pickle((seq, rtype, data))``.  The CRC makes a torn tail
  (half-written record at the moment of the crash) *detectable*:
  :meth:`recover` replays up to the last complete record, truncates the
  garbage in place, and never raises for tail damage — a crash
  mid-append must not become a crash-on-restart loop.

* **Group commit** — ``append()`` writes the record synchronously
  (``O_APPEND`` fd, page cache: the bytes survive a process SIGKILL the
  moment ``write(2)`` returns); ``await flush()`` then awaits an
  ``fsync`` *shared by every handler awaiting in the same event-loop
  window*, so a registration storm pays one disk sync per wave, not
  per actor.  The ``sync`` policy knob (``Config.gcs_wal_sync``):

  - ``"fsync"`` (default) — flush() awaits fsync: survives host power
    loss, not just process death;
  - ``"write"``  — flush() is a no-op after the write: survives
    process SIGKILL (page cache), not a host crash.  Cheaper on real
    disks; identical on tmpfs.

* **Compaction** — the GCS periodically folds the log into the
  existing ``TableStorage`` snapshot and calls :meth:`truncate`;
  records are *idempotent set-style ops* (full-value puts, not deltas)
  so replaying records the snapshot already covers (crash between
  snapshot write and truncate) converges to the same state.

Failpoints: ``gcs.wal.append_fail`` (an append raises/drops — the GCS
degrades to snapshot-only with a counter, never fails the mutation)
and ``gcs.wal.torn_tail`` (the record is half-written, modelling a
crash mid-append — replay must stop cleanly at the previous record).
"""

from __future__ import annotations

import asyncio
import logging
import os
import pickle
import struct
import zlib
from typing import Any, List, Optional, Tuple

from ray_tpu.util import failpoint as _fp

logger = logging.getLogger(__name__)

#: file magic + format version; a file with a different header is not
#: ours (or from a future format) — recovery treats it as cold start
HEADER = b"RTPUWAL1"

_REC = struct.Struct("<II")  # length, crc32


class WalError(Exception):
    """A WAL append/flush failed (caller degrades to snapshot-only)."""


class WriteAheadLog:
    """Append-log of typed ``(rtype, data)`` records with CRC framing,
    torn-tail-tolerant replay, and loop-shared group-commit fsync."""

    def __init__(self, path: str, *, sync: str = "fsync"):
        self.path = path
        self.sync = sync
        self._fd: Optional[int] = None
        self._seq = 0
        # stats (surfaced via GCS debug_state + telemetry)
        self.size_bytes = 0
        self.appends = 0
        self.fsyncs = 0
        self.truncations = 0
        self.replayed_records = 0
        self.torn_tail_bytes = 0
        # group-commit state: _gen counts writes, _synced the highest
        # generation an fsync is known to cover
        self._gen = 0
        self._synced = 0
        self._inflight: Optional[asyncio.Task] = None

    # -- recovery ----------------------------------------------------------
    def recover(self) -> List[Tuple[int, str, Any]]:
        """Replay every complete record, repair a torn tail in place,
        and leave the log open for append.  Never raises for tail
        damage; an unreadable header cold-starts an empty log."""
        records: List[Tuple[int, str, Any]] = []
        good = len(HEADER)
        raw = b""
        try:
            with open(self.path, "rb") as f:
                raw = f.read()
        except OSError:
            raw = b""
        if raw[:len(HEADER)] == HEADER:
            off = len(HEADER)
            while off + _REC.size <= len(raw):
                length, crc = _REC.unpack_from(raw, off)
                body = raw[off + _REC.size:off + _REC.size + length]
                if len(body) < length or zlib.crc32(body) != crc:
                    break  # torn/corrupt tail: stop at the last good one
                try:
                    seq, rtype, data = pickle.loads(body)
                except Exception:  # noqa: BLE001 — undecodable = torn
                    break
                records.append((seq, rtype, data))
                off += _REC.size + length
                good = off
            self.torn_tail_bytes = len(raw) - good
            if self.torn_tail_bytes:
                logger.warning(
                    "WAL %s: discarding %d torn tail bytes after %d "
                    "complete records", self.path, self.torn_tail_bytes,
                    len(records))
        elif raw:
            logger.warning("WAL %s: unrecognized header; cold start",
                           self.path)
            good = len(HEADER)
            records = []
        self.replayed_records = len(records)
        self._seq = (records[-1][0] + 1) if records else 0
        # open for append, truncated to the last complete record (a
        # fresh/foreign file restarts at a clean header)
        self._fd = os.open(self.path,
                           os.O_CREAT | os.O_RDWR | os.O_APPEND, 0o644)
        if raw[:len(HEADER)] != HEADER:
            os.ftruncate(self._fd, 0)
            os.write(self._fd, HEADER)
            good = len(HEADER)
        elif good < len(raw):
            os.ftruncate(self._fd, good)
        self.size_bytes = good
        return records

    # -- append / group commit --------------------------------------------
    def append(self, rtype: str, data: Any) -> None:
        """Write one record (synchronous, ``O_APPEND``).  Raises
        :class:`WalError` on failure — the caller degrades, the
        mutation itself must never fail on WAL trouble."""
        if self._fd is None:
            raise WalError("WAL is closed")
        try:
            # failpoint: the append path fails (raise) or silently
            # loses the record (drop) — GCS degrades to snapshot-only
            if _fp.active() and _fp.failpoint("gcs.wal.append_fail"):
                raise WalError("injected append drop")
            payload = pickle.dumps((self._seq, rtype, data),
                                   protocol=pickle.HIGHEST_PROTOCOL)
            rec = _REC.pack(len(payload), zlib.crc32(payload)) + payload
            if _fp.active() and _fp.failpoint("gcs.wal.torn_tail"):
                # model a crash mid-append: half the record hits disk.
                # Replay must stop at the previous record, silently.
                os.write(self._fd, rec[:max(1, len(rec) // 2)])
                self.size_bytes += max(1, len(rec) // 2)
                self._seq += 1
                self._gen += 1
                return
            # POSIX permits short writes on regular files (ENOSPC,
            # RLIMIT_FSIZE): loop to completion or fail.  A partial
            # record followed by a raise is safe only because the
            # caller degrades (closes the log) on WalError — nothing
            # ever lands after the torn bytes, so replay stops at the
            # last complete record instead of silently dropping
            # acked records written after a tear.
            written = os.write(self._fd, rec)
            while written < len(rec):
                n = os.write(self._fd, rec[written:])
                if n <= 0:
                    raise WalError("WAL short write")
                written += n
        except WalError:
            raise
        except Exception as e:  # noqa: BLE001 — any I/O trouble degrades
            raise WalError(f"WAL append failed: {e}") from e
        self._seq += 1
        self.size_bytes += len(rec)
        self.appends += 1
        self._gen += 1

    async def flush(self) -> None:
        """Await durability of every record appended so far.  With
        ``sync="fsync"`` this awaits an fsync *round* shared with every
        concurrent awaiter (group commit); generation accounting
        guarantees a record appended after a round's syscall entered
        waits for the next round instead of riding a sync that missed
        it."""
        if self._fd is None or self.sync != "fsync":
            return
        target = self._gen
        while self._synced < target:
            t = self._inflight
            if t is not None and t.get_loop() is not \
                    asyncio.get_running_loop():
                # a previous event loop's round (tests churn loops):
                # its result can never be awaited from here — restart
                self._inflight = t = None
            if t is None:
                t = asyncio.get_running_loop().create_task(
                    self._fsync_round())
                self._inflight = t
            try:
                await asyncio.shield(t)
            except asyncio.CancelledError:
                raise
            except Exception as e:  # noqa: BLE001
                raise WalError(f"WAL fsync failed: {e}") from e

    async def _fsync_round(self) -> None:
        gen = self._gen
        loop = asyncio.get_running_loop()
        try:
            await loop.run_in_executor(None, os.fsync, self._fd)
            self.fsyncs += 1
            self._synced = max(self._synced, gen)
        finally:
            self._inflight = None

    # -- compaction --------------------------------------------------------
    def truncate(self) -> None:
        """Drop every record — the snapshot now covers them.  Pending
        flush() awaiters resolve as durable through the snapshot."""
        if self._fd is None:
            return
        os.ftruncate(self._fd, len(HEADER))
        self.size_bytes = len(HEADER)
        self.truncations += 1
        self._synced = self._gen

    def close(self) -> None:
        if self._fd is not None:
            try:
                os.close(self._fd)
            except OSError:
                pass
            self._fd = None
