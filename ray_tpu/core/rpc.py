"""Asyncio message transport used by every control-plane service.

Parity: the reference's gRPC layer (``src/ray/rpc/grpc_server.h``) plus its
long-poll pubsub push channel (``src/ray/pubsub/``).  One framed protocol
covers both: request/reply correlated by message id, and unsolicited PUSH
frames for subscriptions.  Payloads are pickled Python structures; large
tensors never travel this path (they go through the shared-memory object
plane), so pickling cost is bounded by control-message size.

Frame layout: ``[8B LE length][1B version][8B LE msg_id][1B kind]
[payload]`` where payload is ``pickle((method, data))`` and length counts
everything after the length field.  Version, correlation id, and kind
ride the HEADER — outside the pickle — so a frame from an incompatible
peer is rejected with a structured error before any payload bytes are
interpreted (parity: the reference's versioned protobuf schemas).
Payload shapes for the core control-plane methods are declared in
``core/messages.py`` and validated at dispatch.

Transport: a raw ``asyncio.Protocol`` (not StreamReader/Writer) — frames
are parsed in ``data_received`` with zero coroutine overhead and all
frames arriving in one TCP segment dispatch in one tight loop; outbound
frames produced within one event-loop tick coalesce into a single
transport write.  On nop-task storms the reader-coroutine version spent
~40% of loop time in readexactly wakeups.
"""

from __future__ import annotations

import asyncio
import itertools
import logging
import pickle
import random
import struct
import time
from dataclasses import dataclass
from typing import Any, Awaitable, Callable, Dict, Optional, Tuple

from ray_tpu.core.messages import validate as _validate_schema
from ray_tpu.core import telemetry as _tm
from ray_tpu.core import tracing as _trace
from ray_tpu.util import failpoint as _fp

logger = logging.getLogger(__name__)

#: Wire-protocol version (parity: the reference's versioned protobuf
#: schemas).  Carried on EVERY frame header (plus the registration
#: handshakes); a mismatched frame gets a structured per-message
#: rejection at the boundary instead of an unpickle traceback.
#: v3: out-of-band payload frames (KIND_OOB_FLAG + payload-length
#: prefix in the frame body) for the object-transfer data plane.
PROTOCOL_VERSION = 3

_LEN = struct.Struct("<Q")
#: post-length header: [1B version][8B LE msg_id][1B kind]
_HDR = struct.Struct("<BQB")
_PLEN = struct.Struct("<Q")

KIND_REQ = 0
KIND_REP = 1
KIND_ERR = 2
KIND_PUSH = 3
#: kind-byte flag: an out-of-band payload (raw bytes, outside the
#: pickle) is appended to the frame as [8B payload_len][pickle][payload]
KIND_OOB_FLAG = 0x40
KIND_MASK = 0x3F

Address = Tuple[str, int]


class OobPayload:
    """Reply wrapper carrying a bulk buffer OUT of the pickle stream.

    ``meta`` rides the pickled frame body as usual; ``payload`` (any
    bytes-like — typically a pinned object-store arena view) is appended
    to the frame raw.  The object-transfer data plane uses this to cut
    per-chunk copies: the sender never pickles the chunk, and a receiver
    that registered a ``sink`` (see :meth:`Connection.start_call`)
    consumes it straight out of the receive buffer — one copy from
    socket buffer to destination instead of three.  A receiver without a
    sink gets the whole ``OobPayload`` back with ``payload`` as bytes.
    """

    __slots__ = ("meta", "payload")

    def __init__(self, meta: Any, payload):
        self.meta = meta
        self.payload = payload


class RpcError(Exception):
    """Remote handler raised; message carries the remote repr."""


class ConnectionLost(Exception):
    pass


class RpcDeadlineExceeded(RpcError):
    """A retried call chain ran out of its total deadline budget."""


#: Methods safe to retry blindly after they MAY have executed once.
#: Reads are trivially safe; the mutations listed are keyed on a
#: caller-supplied id (node/worker/actor/token) or naturally converge
#: (kv_put overwrites, kv_del/object_release/unsubscribe are no-ops the
#: second time, return_worker/cancel_lease hit an already-settled entry,
#: health_report is per-beat state).  Everything else — push_task(s),
#: push_actor_task(s), request_worker_lease, lease_worker_for_actor,
#: register_job, register_actor, object_create/seal — either executes
#: user code, allocates a resource, or assigns an id, and must only be
#: retried by its caller's own dedup/redispatch logic.
IDEMPOTENT_METHODS = frozenset({
    # pure reads
    "ping", "get_nodes", "kv_get", "kv_keys", "get_actor", "list_actors",
    "get_cluster_load", "get_function", "store_info", "store_stats",
    "debug_state", "get_metrics", "list_jobs", "get_task_events",
    "get_cluster_stats", "list_events", "object_contains", "list_workers",
    "list_objects", "stack_traces", "list_placement_groups",
    "get_object_locations", "object_pull_chunk", "clock_sync", "get_spans",
    "get_trace", "list_traces", "get_timeseries", "get_alerts", "healthz",
    "list_incidents", "get_incident",
    # keyed on (source, pid): a replayed tail dedups in the handler
    "report_flight_tail",
    # keyed / convergent mutations
    "register_node", "register_worker", "subscribe", "unsubscribe",
    "kv_put", "kv_del", "health_report", "actor_started",
    # keyed on each entry's actor_id: a replayed batch returns the
    # existing directory entries instead of re-registering
    "register_actor_batch",
    "object_release", "return_worker", "cancel_lease", "cancel_task",
    # report_spans is deliberately NOT here: its handler appends, so a
    # retry-after-send would duplicate spans (flush loops drop instead)
    "report_metrics", "report_task_events", "drain_node", "reattach_job",
    # transfer bookkeeping: pull_start re-pins idempotently (the holder
    # keeps one pin per link), pull_end/location updates converge
    "object_pull_end", "object_location_added", "object_location_removed",
})


def is_idempotent(method: str) -> bool:
    return method in IDEMPOTENT_METHODS


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter and a total deadline budget
    (parity: the reference GcsRpcClient's retry/backoff and gRPC
    service-config retryPolicy).  ``max_attempts`` counts the first try;
    ``deadline_s`` caps the WHOLE chain — per-attempt timeouts shrink to
    whatever budget remains, so a retried call can never outlive its
    deadline no matter how many attempts fit."""

    max_attempts: int = 5
    base_delay_s: float = 0.1
    max_delay_s: float = 5.0
    multiplier: float = 2.0
    jitter: float = 0.2
    deadline_s: Optional[float] = 30.0

    @classmethod
    def from_config(cls, config=None) -> "RetryPolicy":
        if config is None:
            from ray_tpu.core.config import get_config
            config = get_config()
        deadline = getattr(config, "rpc_call_deadline_s", 30.0)
        return cls(
            max_attempts=max(1, int(getattr(config, "rpc_max_retries", 5))),
            base_delay_s=getattr(config, "rpc_retry_delay_s", 0.1),
            max_delay_s=getattr(config, "rpc_backoff_max_s", 5.0),
            multiplier=getattr(config, "rpc_backoff_multiplier", 2.0),
            jitter=getattr(config, "rpc_backoff_jitter", 0.2),
            deadline_s=deadline if deadline and deadline > 0 else None,
        )

    def backoff_delay(self, retry_index: int, rng: random.Random) -> float:
        raw = min(self.max_delay_s,
                  self.base_delay_s * self.multiplier ** retry_index)
        if self.jitter:
            raw *= 1.0 + self.jitter * (2.0 * rng.random() - 1.0)
        return max(0.0, raw)


#: process-local jitter stream; seeded so a test re-run reproduces the
#: same backoff schedule (determinism > cross-process decorrelation — a
#: cluster's processes still decorrelate via their differing call mixes)
_retry_rng = random.Random(0x52504331)


def gcs_reconnect_delay(attempt: int, config,
                        rng: Optional[random.Random] = None) -> float:
    """Jittered exponential backoff for the GCS reconnect loops (worker
    ``_reconnect_head``, raylet ``_try_gcs_reconnect``).  Full jitter
    (uniform over [half-base, current-ceiling]) instead of a fixed
    sleep: when a whole fleet loses the head at once, decorrelated
    delays keep the restarted GCS from eating every re-registration in
    one synchronized stampede wave.

    ``attempt`` is 0-based; the ceiling is
    ``gcs_reconnect_backoff_base_s * 2**attempt`` capped at
    ``gcs_reconnect_backoff_max_s``."""
    base = max(0.01, float(getattr(config,
                                   "gcs_reconnect_backoff_base_s", 0.2)))
    cap = max(base, float(getattr(config,
                                  "gcs_reconnect_backoff_max_s", 5.0)))
    ceiling = min(cap, base * (2.0 ** max(0, attempt)))
    return (rng or _retry_rng).uniform(base * 0.5, ceiling)


async def call_with_retry(get_conn, method: str, data: Any = None, *,
                          policy: Optional[RetryPolicy] = None,
                          timeout: Optional[float] = None,
                          idempotent: Optional[bool] = None,
                          invalidate: Optional[
                              Callable[[Optional["Connection"]],
                                       None]] = None
                          ) -> Any:
    """One retried call chain with backoff + deadline budget.

    ``get_conn``: async callable returning a live :class:`Connection`
    (called fresh each attempt so the caller can reconnect between
    attempts); ``invalidate`` is called with the FAILED attempt's
    connection (or None if none was obtained) before a retry, so the
    caller can drop exactly that connection from its pool — never a
    fresh one another coroutine raced in.

    Classification: failures while OBTAINING the connection (OSError,
    ConnectionLost, TimeoutError, an armed connect failpoint) are always
    retryable — no request bytes went out.  Failures after the request
    may have been sent (ConnectionLost, per-attempt timeout) are retried
    only when the method is idempotent (callee keyed/convergent — see
    ``IDEMPOTENT_METHODS``) or the caller forces ``idempotent=True``
    because it dedupes.  A structured remote error (``RpcError``) is
    never retried: the peer is healthy and deterministic."""
    if policy is None:
        policy = RetryPolicy.from_config()
    if idempotent is None:
        idempotent = is_idempotent(method)
    loop = asyncio.get_running_loop()
    deadline = (loop.time() + policy.deadline_s
                if policy.deadline_s is not None else None)

    def _remaining() -> Optional[float]:
        if deadline is None:
            return None
        return deadline - loop.time()

    def _attempt_timeout() -> Optional[float]:
        rem = _remaining()
        if rem is None:
            return timeout
        if timeout is None:
            return max(rem, 0.001)
        return max(min(timeout, rem), 0.001)

    last_exc: Optional[BaseException] = None
    failed_conn: Optional[Connection] = None
    chain_start = time.time()
    for attempt in range(policy.max_attempts):
        if attempt:
            if invalidate is not None:
                invalidate(failed_conn)
            failed_conn = None
            delay = policy.backoff_delay(attempt - 1, _retry_rng)
            rem = _remaining()
            if rem is not None and rem <= delay:
                break  # budget can't fund another attempt
            _tm.rpc_retry(method)
            await asyncio.sleep(delay)
        raw = get_conn()
        try:
            conn = await asyncio.wait_for(_ensure_coro(raw),
                                          _attempt_timeout())
        except (ConnectionLost, OSError, asyncio.TimeoutError,
                _fp.FailpointError) as e:
            if hasattr(raw, "close") and not isinstance(raw, Connection):
                raw.close()  # un-awaited coroutine (cancelled pre-start)
            last_exc = e  # nothing was sent: always retryable
            continue
        try:
            result = await conn.call(method, data,
                                     timeout=_attempt_timeout())
        except RpcDeadlineExceeded:
            raise
        except (ConnectionLost, asyncio.TimeoutError,
                _fp.FailpointError) as e:
            last_exc = e
            failed_conn = conn
            if not idempotent:
                raise
            continue
        if attempt:
            # a chain that actually retried is a timeline-worthy anomaly
            _tm.record_span("rpc_retry", f"rpc:{method}", chain_start,
                            time.time(), attempts=attempt + 1,
                            outcome="ok")
        return result
    _tm.rpc_deadline_exceeded(method)
    _tm.record_span("rpc_retry", f"rpc:{method}", chain_start, time.time(),
                    attempts=policy.max_attempts, outcome="deadline",
                    error=f"{type(last_exc).__name__}: {last_exc}")
    raise RpcDeadlineExceeded(
        f"{method} failed after {policy.max_attempts} attempt(s)"
        + (f" within {policy.deadline_s:.1f}s" if policy.deadline_s else "")
        + f": {type(last_exc).__name__}: {last_exc}")


async def _ensure_coro(value):
    # inspect (not asyncio) iscoroutine: the asyncio variant also
    # matches plain generators before 3.11
    import inspect
    if inspect.iscoroutine(value) or isinstance(value, asyncio.Future):
        return await value
    return value


class _FrameProtocol(asyncio.BufferedProtocol):
    """Length-prefixed frame parser bound to one Connection.

    A ``BufferedProtocol``: the transport ``recv_into``s the parse
    buffer directly, so inbound bytes are copied exactly once from the
    socket into ``_buf`` (the default ``Protocol`` path allocates a
    fresh bytes object per recv and we'd append it into the parse buffer
    — two copies per byte, which dominated multi-MiB object-transfer
    frames on slow-memcpy sandboxed hosts)."""

    #: always expose at least this much writable space to recv_into
    _MIN_READ = 256 * 1024

    def __init__(self, handler: Optional["Server"] = None,
                 on_close: Optional[Callable[["Connection"], None]] = None,
                 server_side: bool = False):
        self._handler = handler
        self._on_close = on_close
        self._server_side = server_side
        self._buf = bytearray(self._MIN_READ)
        self._start = 0  # parse position
        self._end = 0    # filled position
        self.conn: Optional[Connection] = None

    def connection_made(self, transport) -> None:
        # large kernel buffers: fewer (expensive) syscalls per transfer
        # frame and less write-pause churn under windowed pulls
        sock = transport.get_extra_info("socket")
        if sock is not None:
            import socket as socket_mod
            for opt in (socket_mod.SO_RCVBUF, socket_mod.SO_SNDBUF):
                try:
                    sock.setsockopt(socket_mod.SOL_SOCKET, opt, 4 << 20)
                except OSError:
                    pass
        self.conn = Connection(transport, self, handler=self._handler,
                               on_close=self._on_close)
        # only server-ACCEPTED links join server.connections / fire the
        # on_connection hook; client-initiated links may carry a handler
        # (so the peer can call back) without being tracked
        if self._server_side and self._handler is not None:
            self._handler._on_connect(self.conn)

    def connection_lost(self, exc) -> None:
        if self.conn is not None:
            self.conn._teardown()

    def pause_writing(self) -> None:
        if self.conn is not None:
            self.conn._writable.clear()

    def resume_writing(self) -> None:
        if self.conn is not None:
            self.conn._writable.set()

    def get_buffer(self, sizehint: int) -> memoryview:
        buf = self._buf
        avail = len(buf) - self._end
        if avail < self._MIN_READ:
            if self._start:
                # compact the consumed prefix (bounded: runs at most
                # once per buffer-full of parsed frames)
                n = self._end - self._start
                buf[:n] = buf[self._start:self._end]
                self._start = 0
                self._end = n
                avail = len(buf) - n
            while avail < self._MIN_READ:
                try:
                    buf += bytes(len(buf))  # double in place
                except BufferError:
                    # someone still exports a view of this buffer (an
                    # arena sink mid-copy on another conn's frame, a
                    # transport read view): bytearray resize is illegal
                    # with live exports, so move the unparsed region to
                    # a fresh buffer instead — the old one stays alive
                    # (and intact) exactly as long as its exports do
                    new = bytearray(max(len(buf) * 2, self._MIN_READ))
                    n = self._end - self._start
                    new[:n] = buf[self._start:self._end]
                    self._buf = buf = new
                    self._start = 0
                    self._end = n
                avail = len(buf) - self._end
        return memoryview(buf)[self._end:]

    def buffer_updated(self, nbytes: int) -> None:
        _tm.add_bytes_received(nbytes)
        self._end += nbytes
        self._parse()
        if self._start == self._end:
            self._start = self._end = 0  # cheap reset, no compaction
            if len(self._buf) > (4 << 20):
                # shrink after a large-transfer backlog: long-lived
                # peer links must not pin their high-water buffer
                self._buf = bytearray(self._MIN_READ)
        elif self._start > (1 << 20):
            # keep long-lived partial frames anchored near the buffer
            # head so get_buffer doesn't keep doubling
            n = self._end - self._start
            self._buf[:n] = self._buf[self._start:self._end]
            self._start = 0
            self._end = n

    def _parse(self) -> None:
        buf = self._buf
        offset = self._start
        total = self._end
        conn = self.conn
        while True:
            if total - offset < 8:
                break
            (length,) = _LEN.unpack_from(buf, offset)
            if total - offset - 8 < length:
                break
            frame_end = offset + 8 + length
            body = offset + 8
            offset = frame_end
            if length < _HDR.size:
                logger.error("runt frame (%d bytes) from %s", length,
                             conn.peername if conn else "?")
                continue
            version, msg_id, kind = _HDR.unpack_from(buf, body)
            if version != PROTOCOL_VERSION:
                # structured per-message rejection BEFORE any payload
                # bytes are interpreted — a mixed-version cluster fails
                # at the boundary with a clear error, not mid-unpickle
                if conn is not None:
                    conn._reject_version(msg_id, kind & KIND_MASK, version)
                continue
            pickle_start = body + _HDR.size
            pickle_end = frame_end
            oob_view = None
            if kind & KIND_OOB_FLAG:
                kind &= KIND_MASK
                if frame_end - pickle_start < _PLEN.size:
                    logger.error("runt OOB frame from %s",
                                 conn.peername if conn else "?")
                    continue
                (oob_len,) = _PLEN.unpack_from(buf, pickle_start)
                pickle_start += _PLEN.size
                if oob_len > frame_end - pickle_start:
                    logger.error("bad OOB length from %s",
                                 conn.peername if conn else "?")
                    continue
                pickle_end = frame_end - oob_len
                oob_view = memoryview(buf)[pickle_end:frame_end]
            try:
                try:
                    method, payload = pickle.loads(
                        memoryview(buf)[pickle_start:pickle_end])
                except Exception:
                    logger.exception("undecodable frame from %s",
                                     conn.peername if conn else "?")
                    continue
                if conn is not None:
                    try:
                        conn._on_frame(msg_id, kind, method, payload,
                                       oob_view)
                    except Exception:
                        # a malformed frame must skip, not fatal-error the
                        # transport and kill every in-flight RPC on the link
                        logger.exception("bad frame from %s", conn.peername)
            finally:
                if oob_view is not None:
                    # the view must be consumed synchronously — a live
                    # export would make buffer compaction/growth raise
                    oob_view.release()
        self._start = offset


class Connection:
    """One bidirectional peer link; usable as client and/or server side."""

    def __init__(self, transport, protocol: _FrameProtocol,
                 handler: Optional["Server"] = None,
                 on_close: Optional[Callable[["Connection"], None]] = None):
        self._transport = transport
        self._protocol = protocol
        self._handler = handler
        self._on_close = on_close
        self._msg_ids = itertools.count(1)
        self._pending: Dict[int, asyncio.Future] = {}
        #: msg_id -> callable(memoryview) consuming a reply's OOB
        #: payload synchronously at frame arrival (object-transfer
        #: chunks land straight in the store arena, no intermediate
        #: bytes object)
        self._payload_sinks: Dict[int, Callable] = {}
        self._push_handler: Optional[Callable[[str, Any], None]] = None
        self._closed = False
        self.peername = transport.get_extra_info("peername")
        # Outbound frames produced within one event-loop tick coalesce
        # into a single transport write (one send(2) instead of one per
        # frame) — the per-frame syscall dominated nop-task storms.
        self._wbuf: list = []
        self._wflush_scheduled = False
        self._loop = asyncio.get_running_loop()
        self._writable = asyncio.Event()
        self._writable.set()
        #: request handlers currently running on this link (drain gate
        #: for graceful process exit — see Connection.drain_outbound)
        self._dispatching = 0
        # Application state slot (e.g. the worker/node this conn belongs to).
        self.context: Dict[str, Any] = {}

    # -- receive path ----------------------------------------------------
    def _reject_version(self, msg_id: int, kind: int, peer_ver: int) -> None:
        if peer_ver == 0x80:
            # pickle protocol magic: the peer speaks the pre-header (v1)
            # framing and cannot parse ANY reply we send — close the link
            # so its RPCs fail fast with ConnectionLost instead of
            # hanging on garbage replies
            logger.error(
                "peer %s speaks the pre-header wire framing (v1); this "
                "process speaks v%d — closing (upgrade the older side)",
                self.peername, PROTOCOL_VERSION)
            self._teardown()
            return
        msg = (f"wire protocol mismatch: frame is v{peer_ver}, this "
               f"process speaks v{PROTOCOL_VERSION} — upgrade the older "
               f"side")
        logger.error("%s (from %s)", msg, self.peername)
        if kind == KIND_REQ and not self._closed:
            # headers are version-stable from v2 on, so the newer peer
            # can correlate this structured rejection to its request
            try:
                self._send_frame(msg_id, KIND_ERR, "_protocol", msg)
            except Exception:
                self._teardown()
        elif kind in (KIND_REP, KIND_ERR):
            # a reply from a mismatched peer: fail OUR pending call with
            # the structured error — dropping it would strand callers
            # that wait without a timeout
            fut = self._pending.pop(msg_id, None)
            if fut is not None and not fut.done():
                fut.set_exception(RpcError(msg))

    def _on_frame(self, msg_id: int, kind: int, method: str,
                  data: Any, oob: Optional[memoryview] = None) -> None:
        if kind == KIND_REQ:
            self._loop.create_task(self._dispatch(msg_id, method, data))
        elif kind == KIND_REP:
            fut = self._pending.pop(msg_id, None)
            sink = self._payload_sinks.pop(msg_id, None)
            if oob is not None:
                if sink is not None:
                    try:
                        sink(oob)
                    except Exception as e:  # noqa: BLE001 — surface to
                        if fut is not None and not fut.done():  # caller
                            fut.set_exception(
                                RpcError(f"payload sink failed: {e!r}"))
                        return
                else:
                    data = OobPayload(data, bytes(oob))
            if fut is not None and not fut.done():
                fut.set_result(data)
        elif kind == KIND_ERR:
            self._payload_sinks.pop(msg_id, None)
            fut = self._pending.pop(msg_id, None)
            if fut is not None and not fut.done():
                fut.set_exception(RpcError(data))
        elif kind == KIND_PUSH:
            try:
                if self._push_handler is not None:
                    self._push_handler(method, data)
                elif self._handler is not None:
                    # server side: route to service push_<channel>
                    self._handler.dispatch_push(self, method, data)
            except Exception:
                logger.exception("push handler failed: %s", method)

    def set_push_handler(self, fn: Callable[[str, Any], None]) -> None:
        self._push_handler = fn

    # -- send path -------------------------------------------------------
    def _send_frame(self, msg_id: int, kind: int, method: str,
                    data: Any) -> None:
        oob = None
        if isinstance(data, OobPayload):
            oob = data.payload
            data = data.meta
            kind |= KIND_OOB_FLAG
        body = pickle.dumps((method, data), protocol=5)
        if oob is None:
            _tm.add_bytes_sent(8 + _HDR.size + len(body))
            self._wbuf.append(_LEN.pack(_HDR.size + len(body)))
            self._wbuf.append(_HDR.pack(PROTOCOL_VERSION, msg_id, kind))
            self._wbuf.append(body)
        else:
            n = len(oob)
            _tm.add_bytes_sent(8 + _HDR.size + _PLEN.size + len(body) + n)
            self._wbuf.append(_LEN.pack(
                _HDR.size + _PLEN.size + len(body) + n))
            self._wbuf.append(_HDR.pack(PROTOCOL_VERSION, msg_id, kind))
            self._wbuf.append(_PLEN.pack(n))
            self._wbuf.append(body)
            # appended as its own buffer: _flush_wbuf hands big items to
            # the transport un-joined, so the bulk bytes go from their
            # source buffer (e.g. a pinned arena view) to the socket
            # without an intermediate copy
            self._wbuf.append(oob)
        if not self._wflush_scheduled:
            self._wflush_scheduled = True
            self._loop.call_soon(self._flush_wbuf)

    #: frames at or above this size are handed to the transport on their
    #: own instead of being joined with neighbors: re-joining multi-MiB
    #: object-transfer chunks copied every chunk an extra time
    _BIG_FRAME = 1 << 20

    def _flush_wbuf(self) -> None:
        self._wflush_scheduled = False
        if not self._wbuf:
            return
        items, self._wbuf = self._wbuf, []
        if self._closed:
            return
        small: list = []
        try:
            for item in items:
                if len(item) >= self._BIG_FRAME:
                    if small:
                        self._transport.write(b"".join(small))
                        small = []
                    self._transport.write(item)
                else:
                    small.append(item)
            if small:
                self._transport.write(
                    small[0] if len(small) == 1 else b"".join(small))
        except Exception:
            self._teardown()

    def _teardown(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._wbuf:
            # hand already-queued frames (e.g. a reply written this tick)
            # to the transport so close() can flush them
            try:
                self._transport.write(b"".join(self._wbuf))
            except Exception:
                pass
            self._wbuf.clear()
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost())
        self._pending.clear()
        self._payload_sinks.clear()
        # wake any drain() waiter parked on a paused transport
        self._writable.set()
        try:
            self._transport.close()
        except Exception:
            pass
        if self._on_close is not None:
            try:
                self._on_close(self)
            except Exception:
                logger.exception("on_close callback failed")

    async def _dispatch(self, msg_id: int, method: str, data: Any) -> None:
        self._dispatching += 1
        # trace-context propagation: a request payload carrying the
        # ``"trace"`` carrier re-activates it for the handler (and for
        # everything the handler awaits — contextvars ride the task).
        # Untraced requests pay one cached-bool check; tracing off pays
        # the same.
        if _trace.enabled() and type(data) is dict:
            tctx = data.get("trace")
            if tctx is not None:
                _trace.set_current(_trace.ctx_of(tctx))
        try:
            try:
                if self._handler is None:
                    raise RpcError(f"no handler for {method}")
                # failpoint: delay/raise/kill BEFORE the handler runs —
                # models a stalled executor / a handler crash (dormant:
                # one module-global truth test)
                if _fp.active():
                    await _fp.afailpoint(f"rpc.{method}.handler_delay")
                result = await self._handler.dispatch(self, method, data)
                reply = (msg_id, KIND_REP, method, result)
            except Exception as e:
                logger.debug("handler %s raised", method, exc_info=True)
                reply = (msg_id, KIND_ERR, method,
                         f"{type(e).__name__}: {e}")
            if _fp.active():
                # failpoint: the handler ran but its reply is lost or
                # late (drop/delay) — the partial failure node-kill
                # chaos can never produce
                if await _fp.afailpoint(f"rpc.{method}.reply_drop"):
                    logger.warning("dropping %s reply (failpoint)", method)
                    return
            if not self._closed:
                try:
                    self._send_frame(*reply)
                except Exception:
                    self._teardown()
        finally:
            self._dispatching -= 1

    def start_call(self, method: str, data: Any = None,
                   sink: Optional[Callable] = None) -> asyncio.Future:
        """Queue the request frame and return the reply future.

        Frames are delivered in ``start_call`` order (the write buffer is
        FIFO and flushed once per loop tick), so callers that need ordered
        delivery (e.g. per-actor sequential submission) can sequence their
        ``start_call``s without waiting for replies.

        ``sink``: consumes the reply's out-of-band payload (a
        ``memoryview`` valid only for the duration of the call) the
        moment the frame arrives; the future then resolves to the
        reply's meta.  Replies without an OOB payload leave the sink
        uncalled.
        """
        if self._closed:
            raise ConnectionLost()
        msg_id = next(self._msg_ids)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        if sink is not None:
            self._payload_sinks[msg_id] = sink
        if _fp.active():
            # failpoint: the request frame is lost on the wire (drop) or
            # the caller crashes at send (raise/kill); the pending
            # future is left to the caller's timeout/deadline budget
            if _fp.failpoint(f"rpc.{method}.request_drop"):
                logger.warning("dropping %s request (failpoint)", method)
                return fut
        self._send_frame(msg_id, KIND_REQ, method, data)
        return fut

    async def call(self, method: str, data: Any = None,
                   timeout: Optional[float] = None,
                   sink: Optional[Callable] = None) -> Any:
        t0 = self._loop.time()
        fut = self.start_call(method, data, sink=sink)
        try:
            if timeout is None:
                return await fut
            return await asyncio.wait_for(fut, timeout)
        finally:
            _tm.rpc_call_observed(method, self._loop.time() - t0)

    def push(self, channel: str, data: Any) -> None:
        """Fire-and-forget push (pubsub delivery, notifications)."""
        if self._closed:
            return
        try:
            self._send_frame(0, KIND_PUSH, channel, data)
        except Exception:
            self._teardown()

    @property
    def closed(self) -> bool:
        return self._closed

    @property
    def pending_dispatches(self) -> int:
        """Request handlers still running on this link (their replies
        are not yet queued)."""
        return self._dispatching

    def outbound_pending(self) -> int:
        """Bytes queued toward the peer: the per-tick coalescing buffer
        plus whatever the transport hasn't handed to the kernel yet."""
        n = sum(len(b) for b in self._wbuf)
        try:
            n += self._transport.get_write_buffer_size()
        except Exception:  # noqa: BLE001 — transport already closed
            pass
        return n

    async def drain_outbound(self, timeout: float = 2.0) -> bool:
        """Wait until every in-flight handler has queued its reply and
        the socket buffer is handed to the kernel (or the link closed).
        Returns False on deadline — the caller decides whether to exit
        anyway.  Used by graceful worker exit so a final reply is never
        torn off mid-flush (a completed task must not be reported as a
        worker crash)."""
        deadline = self._loop.time() + timeout
        while not self._closed and self._loop.time() < deadline:
            self._flush_wbuf()
            if self._dispatching == 0 and self.outbound_pending() == 0:
                return True
            await asyncio.sleep(0.005)
        return self._closed or (self._dispatching == 0
                                and self.outbound_pending() == 0)

    async def drain(self) -> None:
        self._flush_wbuf()
        await self._writable.wait()
        if self._closed:
            raise ConnectionLost()

    def close(self) -> None:
        self._teardown()


class Server:
    """Listens on a port; dispatches ``handle_<method>`` coroutines defined
    on a service object."""

    def __init__(self, service: Any, host: str = "127.0.0.1", port: int = 0,
                 validate_schemas: bool = True):
        self._service = service
        self._host = host
        self._port = port
        #: services whose method names overlap the core control plane
        #: with DIFFERENT payload shapes (e.g. the ray:// client proxy)
        #: opt out — the registry keys on bare method names
        self._validate_schemas = validate_schemas
        self._server: Optional[asyncio.AbstractServer] = None
        self.connections: set[Connection] = set()
        #: optional HandlerStats (util/event_stats.py) — when set, every
        #: dispatched handler records its wall duration (parity:
        #: instrumented_io_context handler stats).  Wall time includes
        #: awaits, so long-poll methods legitimately read "slow".
        self.handler_stats = None

    async def start(self) -> Address:
        loop = asyncio.get_running_loop()
        self._server = await loop.create_server(
            lambda: _FrameProtocol(handler=self,
                                   on_close=self._on_disconnect,
                                   server_side=True),
            self._host, self._port)
        sock = self._server.sockets[0]
        self._host, self._port = sock.getsockname()[:2]
        return (self._host, self._port)

    @property
    def address(self) -> Address:
        return (self._host, self._port)

    def _on_connect(self, conn: Connection) -> None:
        self.connections.add(conn)
        hook = getattr(self._service, "on_connection", None)
        if hook is not None:
            hook(conn)

    def _on_disconnect(self, conn: Connection) -> None:
        self.connections.discard(conn)
        hook = getattr(self._service, "on_disconnection", None)
        if hook is not None:
            hook(conn)

    async def dispatch(self, conn: Connection, method: str, data: Any) -> Any:
        handler: Optional[Callable[..., Awaitable[Any]]] = getattr(
            self._service, f"handle_{method}", None
        )
        if handler is None:
            raise RpcError(f"{type(self._service).__name__} has no method {method}")
        # typed boundary: registered control-plane methods reject
        # malformed payloads with a structured SchemaError naming the
        # method and field (core/messages.py)
        if self._validate_schemas:
            _validate_schema(method, data)
        stats = self.handler_stats
        if stats is None:
            return await handler(conn, data)
        import time as _time

        t0 = _time.monotonic()
        try:
            return await handler(conn, data)
        finally:
            stats.record(method, _time.monotonic() - t0)

    def dispatch_push(self, conn: Connection, channel: str, data: Any) -> None:
        handler = getattr(self._service, f"push_{channel}", None)
        if handler is not None:
            handler(conn, data)

    async def stop(self) -> None:
        # close live connections BEFORE wait_closed(): since 3.12
        # wait_closed blocks until every connection handler finishes
        for conn in list(self.connections):
            conn.close()
        if self._server is not None:
            self._server.close()
            try:
                await asyncio.wait_for(self._server.wait_closed(), 2.0)
            except asyncio.TimeoutError:
                pass


async def connect(address: Address, handler: Optional[Server] = None,
                  timeout: float = 10.0) -> Connection:
    if _fp.active():
        # failpoint: connection establishment fails/stalls — models a
        # peer in a connect() backlog storm or a dropped SYN
        await _fp.afailpoint("rpc.connect")
    loop = asyncio.get_running_loop()
    _, protocol = await asyncio.wait_for(
        loop.create_connection(
            lambda: _FrameProtocol(handler=handler), address[0],
            address[1]),
        timeout)
    assert protocol.conn is not None
    return protocol.conn


class ConnectionPool:
    """Caches one connection per remote address (parity:
    ``core_worker_client_pool.h``)."""

    def __init__(self, handler: Optional[Server] = None):
        self._handler = handler
        self._conns: Dict[Address, Connection] = {}
        self._locks: Dict[Address, asyncio.Lock] = {}

    def get_if_connected(self, address: Address) -> Optional[Connection]:
        """Synchronous: the cached live connection, or None (for loop-
        thread fast paths that must not await)."""
        conn = self._conns.get(address)
        return conn if conn is not None and not conn.closed else None

    async def get(self, address: Address) -> Connection:
        conn = self._conns.get(address)
        if conn is not None and not conn.closed:
            return conn
        lock = self._locks.setdefault(address, asyncio.Lock())
        async with lock:
            conn = self._conns.get(address)
            if conn is not None and not conn.closed:
                return conn
            conn = await connect(address, handler=self._handler)
            self._conns[address] = conn
            return conn

    async def call(self, address: Address, method: str, data: Any = None,
                   *, timeout: Optional[float] = None,
                   policy: Optional[RetryPolicy] = None,
                   idempotent: Optional[bool] = None) -> Any:
        """Retried call through the pool: reconnects between attempts
        (dead cached connections are invalidated) under the policy's
        backoff + deadline budget.  Retry-after-send only happens for
        idempotent methods — see :func:`call_with_retry`."""
        return await call_with_retry(
            lambda: self.get(address), method, data, policy=policy,
            timeout=timeout, idempotent=idempotent,
            invalidate=lambda failed: self.invalidate_conn(address, failed))

    def invalidate(self, address: Address) -> None:
        conn = self._conns.pop(address, None)
        if conn is not None:
            conn.close()

    def invalidate_conn(self, address: Address,
                        conn: Optional[Connection]) -> None:
        """Drop/close exactly ``conn``, and only if this pool still
        caches it — never a fresh connection another coroutine raced in,
        and never a caller-owned link (e.g. the worker's registration
        conn) that merely timed out."""
        if conn is None:
            return
        if self._conns.get(address) is conn:
            self._conns.pop(address, None)
            conn.close()

    def close_all(self) -> None:
        for conn in self._conns.values():
            conn.close()
        self._conns.clear()
