"""Raylet: per-node scheduler, worker pool, and object plane.

Parity: reference ``src/ray/raylet/`` (NodeManager, ClusterTaskManager /
LocalTaskManager, WorkerPool) and ``src/ray/object_manager/`` (ObjectManager
push/pull transfer, LocalObjectManager spill/restore), with the plasma store
role played by the C++ library behind
:class:`ray_tpu.core.object_store.SharedMemoryStore`.

Scheduling model is the reference's lease protocol: submitters ask the
raylet for a worker lease; the raylet grants a local worker (spawning one
if the pool is empty), replies with a *spillback* hint when another node
should run the task, or queues the request.  Granted leases hold their
resources until returned.  The hybrid policy packs onto the local node
until utilization crosses ``scheduler_spread_threshold``, then prefers the
least-loaded feasible remote node (reference
``hybrid_scheduling_policy.h:48``).

Object plane: workers create/seal objects in the node's shared-memory
arena through this service and read them zero-copy via their own mapping.
Missing objects are located through the *owner* (ownership-based object
directory, reference ``ownership_based_object_directory.h``) and pulled in
chunks from the remote raylet.  Primary copies are pinned until the owner
frees them; under memory pressure they are spilled to disk and restored on
demand (reference ``local_object_manager.h``).
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import subprocess
import sys
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu.core import flight_recorder as _flight
from ray_tpu.core import profiler as _prof
from ray_tpu.core import rpc
from ray_tpu.core import telemetry as _tm
from ray_tpu.core import tracing as _trace
from ray_tpu.core.config import Config
from ray_tpu.core.exceptions import ObjectStoreFullError
from ray_tpu.core.ids import NodeID, ObjectID, PlacementGroupID, WorkerID
from ray_tpu.core.object_store import SharedMemoryStore
from ray_tpu.autoscaler.fair_queue import (NODE_ACTIVE, NODE_DRAINED,
                                           NODE_DRAINING, FairQueue,
                                           JobQuota, QuotaExceeded)
from ray_tpu.util import failpoint as _fp

logger = logging.getLogger(__name__)

#: seeded source-sampling stream for pull probes (reproducible runs)
_probe_rng = random.Random(0x52545055)


def _spill_write_failpoint() -> None:
    """Shared chaos site for BOTH spill-tier writers (file and URI):
    the blob write dies mid-flight."""
    _fp.failpoint("raylet.spill.write_fail")


def _restore_read_failpoint() -> None:
    """Shared chaos site for BOTH spill-tier readers (file and URI)."""
    _fp.failpoint("raylet.restore.read_fail")


@dataclass
class WorkerHandle:
    worker_id: WorkerID
    pid: int
    job_id_bin: Optional[bytes]
    conn: rpc.Connection
    task_address: rpc.Address  # the worker's own task server
    proc: Optional[subprocess.Popen] = None
    # whether this process kept the host's accelerator plugin env (slow to
    # import); plain pool workers strip it for fast startup
    tpu_capable: bool = True
    # runtime env this worker has applied (workers are env-dedicated once
    # an env lands on them; parity: runtime-env-keyed WorkerPool)
    env_hash: "Optional[str]" = None
    # lease state
    leased: bool = False
    lease_resources: Dict[str, float] = field(default_factory=dict)
    lease_bundle: Optional[Tuple[bytes, int]] = None  # (pg_id, bundle_index)
    #: whether the leased work survives a kill (owner retries it)
    lease_retriable: bool = True
    lease_granted_at: float = 0.0
    #: token of the acquiring lease request — keys return_worker so a
    #: retried (duplicate) return can never settle a newer lease
    lease_token: Optional[str] = None
    #: chip indices assigned to this lease (parity: raylet GPU-id
    #: assignment backing ray.get_gpu_ids)
    lease_tpu_ids: List[int] = field(default_factory=list)
    lease_tpu_share: float = 0.0
    #: fair-queue job key charged for this lease's in-flight usage —
    #: releases and reconciliation settle against it
    lease_job_key: Optional[str] = None
    is_actor: bool = False
    #: connection of the client holding the current lease (reclaim pushes)
    owner_conn: Optional[rpc.Connection] = None
    #: monotonic time this worker joined the idle pool (pool trimming)
    idle_since: float = 0.0


class _ForkedProc:
    """Popen-compatible handle for a zygote-forked worker (pid only)."""

    __slots__ = ("pid", "returncode")

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
            return None
        except ProcessLookupError:
            self.returncode = -1  # reaped by the zygote's SIGCHLD ignore
            return self.returncode
        except PermissionError:
            return None

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except ProcessLookupError:
            pass

    def terminate(self) -> None:
        import signal as _signal

        self._signal(_signal.SIGTERM)

    def kill(self) -> None:
        import signal as _signal

        self._signal(_signal.SIGKILL)


class _ZygoteClient:
    """Raylet-side handle on the worker fork-server (worker_zygote.py).

    ``spawn`` is a blocking call (write request line, read pid line) —
    the raylet invokes it via ``run_in_executor``; a lock serializes
    concurrent spawns over the single pipe pair."""

    def __init__(self, session_dir: str):
        import threading

        self._session_dir = session_dir
        self._lock = threading.Lock()
        self._proc: Optional[subprocess.Popen] = None

    def _ensure_started(self) -> None:
        if self._proc is not None and self._proc.poll() is None:
            return
        from ray_tpu.core.node import safe_die_with_parent

        env = dict(os.environ)
        env.pop("PALLAS_AXON_POOL_IPS", None)  # no accelerator plugin
        env.pop("RAY_TPU_STASH_AXON_POOL_IPS", None)
        env["RAY_TPU_WORKER"] = "1"
        if safe_die_with_parent():
            env["RAY_TPU_PDEATHSIG"] = str(os.getpid())  # armed in zygote main()
        log = open(os.path.join(self._session_dir, "logs",
                                "worker_zygote.err"), "ab")
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker_zygote"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, stderr=log,
            env=env, text=True, close_fds=False)
        ready = self._proc.stdout.readline()
        if "ready" not in ready:
            raise RuntimeError(f"worker zygote failed to start: {ready!r}")

    def spawn(self, argv, env_updates, log_base) -> int:
        import json as json_mod

        with self._lock:
            self._ensure_started()
            req = {"argv": list(argv), "env": env_updates,
                   "log_base": log_base}
            self._proc.stdin.write(json_mod.dumps(req) + "\n")
            self._proc.stdin.flush()
            reply = self._proc.stdout.readline()
            return int(json_mod.loads(reply)["pid"])

    def stop(self) -> None:
        proc, self._proc = self._proc, None
        if proc is not None:
            try:
                proc.stdin.write('{"exit": true}\n')
                proc.stdin.flush()
            except Exception:
                pass
            proc.terminate()


@dataclass
class PendingLease:
    request: Dict[str, Any]
    future: asyncio.Future
    job_id_bin: Optional[bytes]
    resources: Dict[str, float]
    bundle: Optional[Tuple[bytes, int]]
    env_hash: Optional[str] = None
    env_spawn: Optional[Dict[str, Any]] = None
    retriable: bool = True
    enqueued_at: float = field(default_factory=time.monotonic)
    #: client-generated id so the owner can cancel a request whose
    #: backlog drained before the grant (stale grants churned workers
    #: through grant->instant-return cycles, delaying real demand)
    token: Optional[str] = None
    conn: Optional[rpc.Connection] = None
    #: True once this lease was evaluated with no idle worker available
    #: (warm-pool MISS); grants with it still False count as HITS —
    #: each lease contributes exactly one hit or one miss
    pool_missed: bool = False
    #: fair-queue sub-queue this lease is charged to (job id hex, or a
    #: per-connection key for job-less leases)
    job_key: str = ""
    #: worker picked by the scheduling pass's fits() probe, consumed by
    #: the grant commit in the same pass (never survives across passes)
    granted_worker: Optional[WorkerHandle] = None


class _InflightPull:
    """One in-progress incoming transfer (the receive side of a pull).

    Registered in ``Raylet._inflight_pulls`` so the node can serve
    already-received chunk ranges to OTHER pullers before the copy
    seals: a 1->N broadcast then self-organizes into a tree/chain
    instead of N pulls hammering the one sealed holder (parity:
    ObjectManager registers in-progress copies as pull targets).
    """

    __slots__ = ("size", "offset", "chunk", "have", "waiters", "failed")

    def __init__(self, size: int, offset: int, chunk: int):
        self.size = size
        self.offset = offset  # arena offset of the partial create
        self.chunk = chunk    # chunk stride the ``have`` set is keyed by
        self.have: Set[int] = set()  # completed chunk indices
        self.waiters: List[asyncio.Future] = []
        self.failed = False

    def mark(self, index: int) -> None:
        self.have.add(index)
        self._wake()

    def fail(self) -> None:
        self.failed = True
        self._wake()

    def _wake(self) -> None:
        waiters, self.waiters = self.waiters, []
        for fut in waiters:
            if not fut.done():
                fut.set_result(None)

    def covered(self, start: int, n: int) -> bool:
        last = (start + max(n, 1) - 1) // self.chunk
        return all(i in self.have
                   for i in range(start // self.chunk, last + 1))

    async def wait_range(self, start: int, n: int, timeout: float) -> bool:
        """Block until [start, start+n) has been received (True) or the
        transfer failed / the timeout expired (False)."""
        deadline = time.monotonic() + timeout
        while not self.covered(start, n):
            if self.failed:
                return False
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            fut = asyncio.get_running_loop().create_future()
            self.waiters.append(fut)
            try:
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return False
        return not self.failed


class Raylet:
    def __init__(self, config: Config, gcs_address: rpc.Address,
                 session_dir: str, resources: Optional[Dict[str, float]] = None,
                 node_id: Optional[NodeID] = None,
                 topology: Optional[Dict[str, Any]] = None,
                 host: str = "127.0.0.1", port: int = 0):
        self.config = config
        self.gcs_address = gcs_address
        self.session_dir = session_dir
        self.node_id = node_id or NodeID.from_random()
        self.topology = topology or {}
        self.server = rpc.Server(self, host=host, port=port)
        self.pool = rpc.ConnectionPool()  # raylet->raylet, raylet->owner
        self.gcs_conn: Optional[rpc.Connection] = None

        if resources is None:
            resources = {"CPU": float(os.cpu_count() or 1)}
        resources.setdefault("CPU", float(os.cpu_count() or 1))
        self.resources_total = dict(resources)
        self.resources_available = dict(resources)

        # object store
        store_capacity = config.object_store_memory
        if store_capacity <= 0:
            store_capacity = min(
                int(os.sysconf("SC_PAGE_SIZE") * os.sysconf("SC_PHYS_PAGES")
                    * 0.3),
                16 * 1024 ** 3,
            )
        store_path = os.path.join(
            "/dev/shm" if os.path.isdir("/dev/shm") else session_dir,
            f"rtpu_store_{self.node_id.hex()[:12]}",
        )
        self.store = SharedMemoryStore(
            store_path, store_capacity,
            shards=getattr(config, "store_metadata_shards", 0))
        self.store_capacity = store_capacity
        self._primary: Set[ObjectID] = set()  # pinned primaries
        # jobs whose arena-bytes gauge was non-zero last flush (zeroed
        # once their primaries drain — see _sample_job_arena_bytes)
        self._job_arena_reported: Set[str] = set()
        self._owner_of: Dict[ObjectID, tuple] = {}  # id -> owner address tuple
        self._spilled: Dict[ObjectID, str] = {}  # id -> file path / uri
        self._spilled_sizes: Dict[ObjectID, int] = {}  # id -> payload bytes
        self._spill_bytes = 0  # bytes resident in the spill tier
        self._spill_lock: Optional[asyncio.Lock] = None  # one sweep at a time
        self._spill_ahead_running = False  # one background sweep at a time
        # restores whose blob read / arena write is in flight:
        # id -> [active restore count, freed-mid-restore flag].
        # handle_object_free must NOT store.delete these (the unsealed
        # pin-0 entry would free instantly and the executor thread's
        # write would scribble over whatever re-allocates the block);
        # it sets the flag and the LAST restore's guard-exit deletes.
        # Refcounted, not a bare flag: concurrent restores of one oid
        # are reachable (pull_start's URI path races _make_local), and
        # a second restore's exit must not strip the first's guard.
        self._restoring: Dict[ObjectID, list] = {}
        self._spill_dir = config.object_spilling_directory or os.path.join(
            session_dir, "spill")
        os.makedirs(self._spill_dir, exist_ok=True)
        # per-object pull serialization: oid -> [lock, waiter_count]; the
        # entry is dropped when the last waiter leaves (a bare
        # setdefault'd Lock leaked one dict entry per object pulled)
        self._pull_locks: Dict[ObjectID, list] = {}
        # in-progress incoming transfers, served to other pullers as
        # *partial* sources (emergent broadcast trees; ObjectManager
        # parity: in-progress copies are registered pull targets)
        self._inflight_pulls: Dict[ObjectID, _InflightPull] = {}
        # same-host peer arenas mapped for the shm transfer fast path:
        # store path -> (mmap, base address, ctypes export)
        self._peer_arenas: Dict[str, tuple] = {}

        # worker pool: spawned-but-unregistered procs as
        # (proc, tpu_capable, spawned_with_needs_tpu, spawn_token)
        self._spawned_procs: List[Tuple[Any, bool, bool, Any]] = []
        self.workers: Dict[WorkerID, WorkerHandle] = {}
        self._idle: List[WorkerHandle] = []
        self._starting = 0
        self._starting_tpu = 0  # subset of _starting spawned with needs_tpu
        # isolated-runtime-env worker spawns (venv/conda/container):
        # env_hash -> in-flight count, spawn token -> env_hash (tokens,
        # not pids: container workers see a private pid namespace),
        # env_hash -> build error
        self._starting_env: Dict[str, int] = {}
        self._env_spawn_hash: Dict[str, str] = {}
        self._env_broken: Dict[str, str] = {}
        # weighted-fair lease queue with per-job quotas (pure math in
        # ray_tpu/autoscaler/fair_queue.py; this class feeds it events).
        # Job-less leases key by connection, so multi-client round-robin
        # degenerates to the pre-quota behavior.
        self._fair = FairQueue(resources_of=lambda lease: lease.resources)
        # quota keys installed from the GCS table (health-ack piggyback
        # + "quotas" pubsub); tracked so removals propagate
        self._gcs_quota_jobs: Set[str] = set()
        # node lifecycle (docs/autoscaler.md): while True this raylet
        # grants nothing — new lease requests spill to ACTIVE peers and
        # the drain protocol migrates the object plane before release
        self._draining = False
        self._drain_task: Optional[asyncio.Task] = None
        self._register_waiters: List[asyncio.Future] = []
        # cluster profiling window state (profiler_control): kept so
        # workers that register MID-window join it via the register
        # reply instead of sampling nothing
        self._profiler_state: Optional[Dict[str, Any]] = None
        max_workers = config.max_workers_per_node
        self._max_workers = max_workers if max_workers > 0 else int(
            4 * self.resources_total.get("CPU", 1))

        # placement-group bundles: (pg_id, idx) -> remaining resources
        self._bundles: Dict[Tuple[bytes, int], Dict[str, float]] = {}
        self._bundle_totals: Dict[Tuple[bytes, int], Dict[str, float]] = {}

        # cluster view for spillback (refreshed from GCS health replies)
        self._cluster_view: List[Dict[str, Any]] = []
        # node_id -> (spill count, last-charge time): local charge for
        # spill decisions between resource-view broadcasts
        self._spill_pressure: Dict[bytes, Tuple[float, float]] = {}
        # per-chip fractional load for TPU-id assignment (whole-chip
        # leases get disjoint ids because availability gating keeps the
        # total demand <= chip count)
        self._tpu_load: Dict[int, float] = {
            i: 0.0 for i in range(int(self.resources_total.get("TPU", 0)))}
        # rate limiter for reclaim_idle nudges under pool-cap contention
        self._last_reclaim_push = 0.0
        self._reclaim_timer_armed = False
        self._reclaim_retry_delay = 0.03
        # decaying count of workers claimed by actors recently: actor
        # waves permanently consume pool workers, so the refill target
        # tracks recent claim volume (parity: GcsActorScheduler keeps
        # nodes stocked for the wave it is placing) and decays back to
        # the boot watermark when the storms stop
        self._actor_claims = 0.0
        self._actor_claims_ts = time.monotonic()
        # decaying PEAK of the pending-lease backlog: demand feeds the
        # warm-pool target, so a wave that queued behind cold spawns
        # rebuilds enough warm forks for the NEXT wave of that size
        self._backlog_demand = 0.0
        self._backlog_demand_ts = time.monotonic()
        # actor creation tasks currently executing on this node's
        # workers: the warm-pool rebuild stays parked while >0 (spawn
        # storms mid-wave steal the CPU the wave itself needs)
        self._creating_actors = 0
        # True while a lease batch enqueues: _maybe_schedule holds off
        # so the whole wave lands in ONE scheduling pass (per-enqueue
        # passes over a growing queue were O(n^2) in the batch size)
        self._sched_suspended = False
        # log monitor state: file path -> (offset, pid)
        self._log_pids: Dict[str, int] = {}
        self._log_offsets: Dict[str, int] = {}
        self._tasks: List[asyncio.Task] = []
        self._closing = False
        # monotonic metrics-flush seq (the GCS drops replayed flushes)
        self._metrics_report_seq = 0

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------
    async def start(self) -> rpc.Address:
        address = await self.server.start()
        self.address = address
        # carry our handler so the GCS can call back over the
        # registration link (profiler_control fan-out) without opening
        # a second connection
        self.gcs_conn = await rpc.connect(self.gcs_address,
                                          handler=self.server)
        reply = await self.gcs_conn.call("register_node", {
            "node_id": self.node_id.binary(),
            "raylet_address": address,
            "protocol_version": rpc.PROTOCOL_VERSION,
            "resources": self.resources_total,
            "topology": self.topology,
            # worker capacity: a dedicated control node (0 CPUs → cap
            # 0) must never be handed an actor lease it can't serve
            "max_workers": self._max_workers,
            # the GCS reads this raylet's flight ring by pid if the
            # node dies (incident journal, docs/observability.md)
            "pid": os.getpid(),
        })
        # adopt the cluster-wide config decided by the head node
        self.config = Config.from_json(reply["config"])
        # adopt the durable lifecycle verdict + quota table: a raylet
        # re-registering after a GCS restart mid-drain resumes DRAINING
        # instead of silently re-opening its lease plane
        self._apply_gcs_state(reply.get("state"))
        self._apply_quotas(reply.get("quotas"))
        # join an in-progress cluster profiling window (node added
        # mid-`ray-tpu profile`)
        prof = reply.get("profiler")
        if prof and prof.get("enabled"):
            _prof.configure(True, hz=prof.get("hz"),
                            duration_s=prof.get("duration_s"))
            self._profiler_state = {
                "enabled": True, "hz": prof.get("hz"),
                "deadline": (time.monotonic() + prof["duration_s"]
                             if prof.get("duration_s") else None)}
        # adopt cluster-armed failpoints (see util/failpoint.py; no-op
        # unless a chaos test armed sites in the GCS KV)
        await _fp.sync_from_kv(self.gcs_conn)
        loop = asyncio.get_running_loop()
        from ray_tpu.util import event as event_mod
        self._event_mod = event_mod
        event_mod.init("RAYLET", self.session_dir, gcs_conn=self.gcs_conn,
                       loop=loop)
        # crash-surviving flight ring (head node: the GCS opened the
        # process ring already and this is a no-op — first init wins)
        _flight.init("raylet", self.session_dir, self.config)
        # versioned resource-view subscription (parity: ray_syncer —
        # delta broadcasts replace per-beat full-table polling)
        self._view_by_id: Dict[bytes, Dict[str, Any]] = {}
        self._view_version = 0
        self._view_stale = True
        self._view_subscribed = False
        self.gcs_conn.set_push_handler(self._on_gcs_push)
        await self.gcs_conn.call("subscribe", {"channel": "resource_view"})
        self._view_subscribed = True
        # quota updates push immediately; the health-report ack
        # re-carries the full table each beat as the catch-up path
        try:
            await self.gcs_conn.call("subscribe", {"channel": "quotas"})
        except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError):
            pass
        if getattr(self.config, "event_stats", True):
            from ray_tpu.util.event_stats import HandlerStats, LoopMonitor
            self.server.handler_stats = HandlerStats()
            self._loop_monitor = LoopMonitor(
                f"raylet-{self.node_id.hex()[:8]}",
                self.server.handler_stats)
            self._loop_monitor.start()
        self._tasks.append(loop.create_task(self._health_loop()))
        self._tasks.append(loop.create_task(self._reap_loop()))
        self._tasks.append(loop.create_task(self._log_monitor_loop()))
        self._tasks.append(loop.create_task(self._metrics_flush_loop()))
        # always-on profiling mode (profiler_enabled): sample this
        # raylet's own loop/executor threads too
        _prof.maybe_start_from_config()
        if self.config.memory_monitor_refresh_ms > 0 and \
                self.config.memory_usage_threshold > 0:
            self._tasks.append(
                loop.create_task(self._memory_monitor_loop()))
        n_prestart = self.config.num_prestart_workers
        if n_prestart < 0:
            n_prestart = min(8, 2 * int(self.resources_total.get("CPU", 1)))
        self._prestart_watermark = n_prestart
        for _ in range(n_prestart):
            self._start_worker(None)
        logger.info("raylet %s on %s resources=%s",
                    self.node_id.hex()[:12], address, self.resources_total)
        return address

    async def stop(self) -> None:
        self._closing = True
        if getattr(self, "_loop_monitor", None) is not None:
            self._loop_monitor.stop()
        if getattr(self, "_zygote", None) is not None:
            self._zygote.stop()
        for t in self._tasks:
            t.cancel()
        for w in list(self.workers.values()):
            if w.proc is not None:
                w.proc.terminate()
        await self.server.stop()
        if self.gcs_conn:
            self.gcs_conn.close()
        self.pool.close_all()
        for path in list(self._peer_arenas):
            ent = self._peer_arenas.pop(path)
            ent[2] = None  # drop the ctypes export before unmapping
            try:
                ent[0].close()
            except BufferError:
                pass  # export still referenced; process teardown
        self.store.close()
        _flight.close(unlink=True)  # graceful stop: no crash evidence

    def _on_gcs_push(self, channel: str, data: Any) -> None:
        if channel == "quotas":
            self._apply_quotas(data.get("quotas"))
            return
        if channel != "resource_view":
            return
        version = data.get("version", 0)
        if self._view_stale or version != self._view_version + 1:
            # gap (missed a broadcast, or fresh connection): resync with
            # one full fetch — the syncer contract (versioned deltas +
            # snapshot-on-gap, ray_syncer.h)
            self._view_version = version
            self._view_stale = True
            return
        self._view_version = version
        for entry in data.get("nodes", []):
            self._view_by_id[bytes(entry["node_id"])] = entry
        self._cluster_view = list(self._view_by_id.values())
        self._maybe_schedule()  # fresh capacity may unblock queued work

    async def _resync_view(self) -> None:
        version_before = self._view_version
        view = await self.gcs_conn.call("get_nodes", {}, timeout=5.0)
        self._view_by_id = {bytes(n["node_id"]): n for n in view}
        self._cluster_view = list(self._view_by_id.values())
        # deltas that landed during the await were dropped (stale mode)
        # but may POSTDATE this snapshot (e.g. a node death that never
        # re-dirties) — refetch next beat rather than trusting it
        self._view_stale = self._view_version != version_before
        self._maybe_schedule()

    async def _health_loop(self) -> None:
        while not self._closing:
            try:
                # re-anchor the fair queue's advisory in-flight ledger
                # on ground truth (live leases) each beat: dropped
                # accounting updates (raylet.quota.account_drop, crash
                # paths) converge instead of wedging a job forever
                self._fair.reconcile(self._lease_usage_truth())
                reply = await self.gcs_conn.call("health_report", {
                    "node_id": self.node_id.binary(),
                    "resources_available": self.resources_available,
                    "load": self._fair.pending_count(),
                    # queued resource shapes drive autoscaling (parity:
                    # resource_load_by_shape in the reference's syncer)
                    "pending_demand": [lease.resources for lease in
                                       self._fair.pending()[:100]],
                    # per-job in-flight usage: the GCS WALs it per node
                    # so quota accounting survives a head SIGKILL
                    "lease_usage": self._fair.export_usage(),
                    # per-node reporter payload (parity:
                    # dashboard/modules/reporter) — node cpu/mem plus
                    # per-worker cpu%/rss
                    "node_stats": self._collect_node_stats(),
                }, timeout=5.0)
                if not reply.get("acked"):
                    logger.error("GCS rejected health report; exiting raylet")
                    break
                self._apply_gcs_state(reply.get("state"))
                self._apply_quotas(reply.get("quotas"))
                if not self._view_subscribed:
                    # a re-register's subscribe failed: retry every beat
                    # (without the subscription the view would freeze on
                    # its last snapshot forever)
                    try:
                        await self.gcs_conn.call(
                            "subscribe", {"channel": "resource_view"},
                            timeout=5.0)
                        self._view_subscribed = True
                        self._view_stale = True  # catch missed deltas
                    except (rpc.ConnectionLost, rpc.RpcError,
                            asyncio.TimeoutError):
                        pass
                if self._view_stale:
                    # deltas flow via the resource_view subscription; a
                    # full fetch happens only on startup or version gap
                    await self._resync_view()
                self._gcs_misses = 0
            except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError):
                if self._closing:
                    break
                self._gcs_misses = getattr(self, "_gcs_misses", 0) + 1
                _tm.heartbeat_miss()
                logger.warning("GCS unreachable from raylet %s (%d)",
                               self.node_id.hex()[:12], self._gcs_misses)
                # the GCS may be RESTARTING (reference: raylets buffer
                # through a GCS restart and re-register —
                # test_gcs_fault_tolerance.py): reconnect + re-register
                # with the same node id before giving up.  Attempts are
                # gated by a jittered exponential backoff clock so a
                # fleet-wide head restart doesn't stampede every raylet
                # into synchronized once-per-beat re-registration.
                now = time.monotonic()
                if now >= getattr(self, "_gcs_reconnect_next", 0.0):
                    self._gcs_reconnect_next = now + rpc.gcs_reconnect_delay(
                        getattr(self, "_gcs_reconnect_attempts", 0),
                        self.config)
                    self._gcs_reconnect_attempts = getattr(
                        self, "_gcs_reconnect_attempts", 0) + 1
                    if await self._try_gcs_reconnect():
                        self._gcs_misses = 0
                        self._gcs_reconnect_attempts = 0
                        self._gcs_reconnect_next = 0.0
                        continue
                if self._gcs_misses * self.config.health_report_period_s > \
                        self.config.health_timeout_s * 3:
                    # head is gone for good: tear down this node (workers
                    # follow via their raylet connections dropping)
                    logger.error("GCS dead; raylet exiting")
                    os._exit(0)
            await asyncio.sleep(self.config.health_report_period_s)

    async def _try_gcs_reconnect(self) -> bool:
        try:
            conn = await rpc.connect(self.gcs_address, timeout=3.0,
                                     handler=self.server)
            reply = await conn.call("register_node", {
                "node_id": self.node_id.binary(),
                "raylet_address": list(self.address),
                "protocol_version": rpc.PROTOCOL_VERSION,
                "resources": self.resources_total,
                "topology": self.topology,
                "max_workers": self._max_workers,
            }, timeout=5.0)
            if self.gcs_conn is not None:
                self.gcs_conn.close()
            self.gcs_conn = conn
            conn.set_push_handler(self._on_gcs_push)
            self._view_stale = True
            self._view_subscribed = False
            try:
                await conn.call("subscribe", {"channel": "resource_view"},
                                timeout=5.0)
                self._view_subscribed = True
            except (rpc.ConnectionLost, rpc.RpcError,
                    asyncio.TimeoutError):
                pass  # the health loop retries each beat
            # resume the durable lifecycle verdict: a GCS restart
            # mid-drain must not re-open a DRAINING node's lease plane
            self._apply_gcs_state(reply.get("state"))
            self._apply_quotas(reply.get("quotas"))
            logger.info("raylet %s re-registered with restarted GCS",
                        self.node_id.hex()[:12])
            return bool(reply)
        except (rpc.ConnectionLost, rpc.RpcError, OSError,
                asyncio.TimeoutError):
            return False

    # ------------------------------------------------------------------
    # node lifecycle + quota plane (docs/autoscaler.md)
    # ------------------------------------------------------------------
    def _lease_usage_truth(self) -> Dict[str, Dict[str, float]]:
        """Per-job in-flight resources from the LIVE lease table (the
        granted workers themselves) — the ground truth the fair queue's
        advisory ledger reconciles against."""
        truth: Dict[str, Dict[str, float]] = {}
        for w in self.workers.values():
            if w.leased and w.lease_job_key:
                usage = truth.setdefault(w.lease_job_key, {})
                for k, v in w.lease_resources.items():
                    usage[k] = usage.get(k, 0.0) + v
        return truth

    def _apply_gcs_state(self, state: Optional[str]) -> None:
        """Adopt the GCS's durable lifecycle verdict for this node.
        DRAINING/DRAINED closes the lease plane (a head restart
        mid-drain re-delivers the verdict here); ACTIVE re-opens it —
        the GCS aborted the drain, so any still-running local drain is
        cancelled and queued leases get scheduled again."""
        if state is None:
            return
        if state in (NODE_DRAINING, NODE_DRAINED):
            if not self._draining:
                logger.info("raylet %s entering %s (GCS verdict)",
                            self.node_id.hex()[:12], state)
                self._draining = True
        elif self._draining:
            task, self._drain_task = self._drain_task, None
            if task is not None and not task.done():
                task.cancel()
            self._draining = False
            logger.info("raylet %s back to ACTIVE (drain aborted)",
                        self.node_id.hex()[:12])
            self._maybe_schedule()

    def _apply_quotas(self, quotas: Optional[Dict[str, Any]]) -> None:
        """Install the GCS quota table (full-state replace: jobs gone
        from the table lose their local quota too)."""
        if quotas is None:
            return
        fresh: Set[str] = set()
        for job, q in quotas.items():
            try:
                self._fair.set_quota(job, JobQuota.from_dict(q))
            except Exception:  # noqa: BLE001 — one bad row, not all
                continue
            fresh.add(job)
        for job in self._gcs_quota_jobs - fresh:
            self._fair.remove_quota(job)
        self._gcs_quota_jobs = fresh

    async def handle_drain(self, conn, data):
        """GCS-driven graceful drain (docs/autoscaler.md): quiesce the
        lease plane, migrate every pinned primary + local spill blob to
        an ACTIVE peer, and reply ok only when NOTHING on this node is
        the last copy of anything.  Any failure replies not-ok — the
        GCS aborts the drain and this node goes back to serving with
        its object plane untouched (the success path is the only one
        that releases pins)."""
        peers = [p for p in data.get("peers", [])
                 if bytes(p["node_id"]) != self.node_id.binary()]
        task = self._drain_task
        if task is None:
            self._draining = True
            task = self._drain_task = asyncio.ensure_future(
                self._drain_impl(peers))
        try:
            # shield: a dropped GCS connection mid-drain must not kill
            # the migration — the GCS retry coalesces onto this task
            result = await asyncio.shield(task)
        except asyncio.CancelledError:
            if not task.cancelled():
                # the HANDLER was cancelled (connection torn down),
                # not the drain — shield kept the migration running
                raise
            # cancelled by _apply_gcs_state (GCS-side abort): the node
            # is already back to ACTIVE there
            return {"ok": False, "error": "drain cancelled"}
        except Exception as e:  # noqa: BLE001 — abort, stay serving
            result = {"ok": False, "error": f"{type(e).__name__}: {e}"}
        if not result.get("ok"):
            self._draining = False
            self._drain_task = None
            self._maybe_schedule()
        return result

    def _respill_queued(self) -> Optional[str]:
        """Move every queued lease to an ACTIVE peer; returns an error
        string when one cannot move (pinned demand, or no feasible
        peer) — the drain must abort so the request is served HERE."""
        for lease in self._fair.pending():
            if lease.future.done():
                self._fair.remove(lease)
                continue
            spill = None
            if lease.bundle is None:
                spill = self._pick_spillback(lease.resources,
                                             lease.request,
                                             force_remote=True)
            if spill is None:
                return ("queued lease %s cannot move to a peer"
                        % (lease.resources,))
            self._fair.remove(lease)
            lease.future.set_result({"spillback": spill})
        return None

    async def _drain_impl(self, peers: List[Dict[str, Any]]
                          ) -> Dict[str, Any]:
        # 1) actors pin their host: their in-memory state can't migrate
        actors = sum(1 for w in self.workers.values() if w.is_actor)
        if actors:
            return {"ok": False,
                    "error": f"{actors} actor(s) hosted on node"}
        # 2) wait (bounded) for in-flight task leases to come home,
        #    nudging owners to cut their idle-lease grace short
        deadline = time.monotonic() + max(
            1.0, 0.4 * getattr(self.config, "drain_timeout_s", 60.0))
        while any(w.leased for w in self.workers.values()):
            for w in list(self.workers.values()):
                c = w.owner_conn
                if w.leased and c is not None and not c.closed:
                    c.push("reclaim_idle", {})
            if time.monotonic() > deadline:
                n = sum(1 for w in self.workers.values() if w.leased)
                return {"ok": False,
                        "error": f"{n} lease(s) still in flight"}
            await asyncio.sleep(0.05)
        # 3) queued leases move to peers (or the drain aborts)
        err = self._respill_queued()
        if err is not None:
            return {"ok": False, "error": err}
        # 4) object migration: every pinned primary and every local
        #    spill blob gets adopted (pulled + re-pinned) by a peer
        #    BEFORE this node drops anything.  URI-spilled blobs
        #    already outlive this node — the owner holds the URI.
        to_move: List[Tuple[ObjectID, bool]] = \
            [(oid, False) for oid in self._primary]
        to_move += [(oid, True) for oid, target in self._spilled.items()
                    if "://" not in target and oid not in self._primary]
        if to_move and not peers:
            return {"ok": False,
                    "error": "no ACTIVE peers to adopt objects"}
        migrated = spill_handed_off = 0
        rr = 0
        for oid, spilled in to_move:
            adopted = None
            for attempt in range(len(peers)):
                peer = peers[(rr + attempt) % len(peers)]
                try:
                    pconn = await self.pool.get(tuple(peer["address"]))
                    owner = self._owner_of.get(oid)
                    reply = await pconn.call("adopt_object", {
                        "object_id": oid.binary(),
                        "owner": list(owner) if owner else None,
                        "source": list(self.server.address),
                        "spilled": spilled,
                    }, timeout=30.0)
                except (rpc.ConnectionLost, rpc.RpcError,
                        asyncio.TimeoutError, OSError):
                    continue
                if reply and reply.get("ok"):
                    adopted = reply
                    break
            rr += 1
            if adopted is None:
                return {"ok": False,
                        "error": f"migration of {oid.hex()[:12]} failed"}
            # byte-identity guard: the adopted copy must be the size we
            # hold (content equality rides the pull protocol's chunking)
            expect = self._spilled_sizes.get(oid)
            if expect is None:
                lease = self.store.lease(oid)
                if lease is not None:
                    expect = lease[1]
                    self.store.release(oid)
            if expect is not None and adopted.get("size") != expect:
                return {"ok": False,
                        "error": f"adopted copy of {oid.hex()[:12]} is "
                                 f"{adopted.get('size')} bytes, "
                                 f"expected {expect}"}
            # hand-off complete: drop OUR claim.  The arena copy left
            # behind is a plain evictable secondary on a node about to
            # terminate; the spill blob is deleted outright.
            if spilled:
                target = self._spilled.pop(oid, None)
                self._spill_bytes -= self._spilled_sizes.pop(oid, 0)
                if target is not None:
                    await asyncio.get_running_loop().run_in_executor(
                        None, self._delete_spill_blob, target)
                spill_handed_off += 1
            else:
                self._primary.discard(oid)
                self.store.release(oid)
                migrated += 1
        # 5) leases that arrived during the migration: move or abort
        err = self._respill_queued()
        if err is not None:
            return {"ok": False, "error": err}
        logger.info("raylet %s drained: %d primaries migrated, %d "
                    "spill blobs handed off", self.node_id.hex()[:12],
                    migrated, spill_handed_off)
        return {"ok": True, "migrated": migrated,
                "spill_handed_off": spill_handed_off}

    async def handle_adopt_object(self, conn, data):
        """Drain-migration target (peer side): pull the object — via
        the owner's directory when it has one, so the transfer chains
        like any broadcast pull, else straight from the draining source
        — and pin it as OUR primary before the drainer releases."""
        oid = ObjectID(data["object_id"])
        owner = tuple(data["owner"]) if data.get("owner") else None
        ok = self.store.contains(oid)
        if not ok and owner is not None:
            ok = await self._make_local(oid, owner,
                                        time.monotonic() + 25.0)
        if not ok and data.get("source"):
            src = tuple(data["source"])
            ok = await self._pull_object(oid, [src], [], None)
        if not ok:
            return {"ok": False, "error": "pull failed"}
        lease = self.store.lease(oid)
        if lease is None:
            return {"ok": False, "error": "adopted copy vanished"}
        size = lease[1]
        self.store.release(oid)
        self._mark_primary(oid, owner)
        return {"ok": True, "size": size}

    # ------------------------------------------------------------------
    # memory monitor + worker killing policy (parity:
    # src/ray/common/memory_monitor.h:52, raylet/worker_killing_policy.h:30)
    # ------------------------------------------------------------------
    @staticmethod
    def _memory_used_fraction() -> float:
        """Host memory pressure from /proc/meminfo (MemAvailable)."""
        try:
            total = avail = None
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        total = float(line.split()[1])
                    elif line.startswith("MemAvailable:"):
                        avail = float(line.split()[1])
                    if total is not None and avail is not None:
                        break
            if not total or avail is None:
                # unknown availability must read as "no pressure", not
                # 100% used — else the monitor becomes a kill loop
                return 0.0
            return 1.0 - avail / total
        except OSError:
            return 0.0

    def _pick_oom_victim(self) -> Optional[WorkerHandle]:
        """Retriable-LIFO (reference policy): among leased workers,
        prefer retriable plain tasks (owners resubmit them), newest
        lease first; non-retriable tasks next; actors only as the last
        resort (killing one loses state)."""
        leased = [w for w in self.workers.values()
                  if w.leased and w.proc is not None]
        for group in (
            [w for w in leased if not w.is_actor and w.lease_retriable],
            [w for w in leased if not w.is_actor and not w.lease_retriable],
            [w for w in leased if w.is_actor],
        ):
            if group:
                return max(group, key=lambda w: w.lease_granted_at)
        return None

    def _collect_node_stats(self) -> Dict[str, Any]:
        """Node + per-worker process stats (parity: the reference's
        dashboard reporter agent collecting psutil stats per node)."""
        try:
            import psutil
        except ImportError:
            return {}
        try:
            vm = psutil.virtual_memory()
            stats: Dict[str, Any] = {
                "cpu_percent": psutil.cpu_percent(interval=None),
                "mem_percent": vm.percent,
                "mem_used": int(vm.used),
                "mem_total": int(vm.total),
                "workers": [],
            }
            for w in list(self.workers.values()):
                try:
                    p = psutil.Process(w.pid)
                    with p.oneshot():
                        stats["workers"].append({
                            "pid": w.pid,
                            "worker_id": w.worker_id.hex(),
                            "cpu_percent": p.cpu_percent(interval=None),
                            "rss": int(p.memory_info().rss),
                            "is_actor": bool(w.is_actor),
                        })
                except (psutil.NoSuchProcess, psutil.AccessDenied):
                    pass
            return stats
        except Exception:  # noqa: BLE001 — stats must never hurt health
            return {}

    async def _memory_monitor_loop(self) -> None:
        period = self.config.memory_monitor_refresh_ms / 1000.0
        threshold = self.config.memory_usage_threshold
        while not self._closing:
            await asyncio.sleep(period)
            try:
                used = self._memory_used_fraction()
                if used <= threshold:
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                logger.warning(
                    "memory pressure %.0f%% > %.0f%%: killing worker "
                    "%s (pid %d) to protect the node; its task will be "
                    "retried", used * 100, threshold * 100,
                    victim.worker_id.hex()[:12], victim.pid)
                victim.proc.kill()
                self._event_mod.emit(
                    "ERROR", "OOM_KILL",
                    f"memory monitor killed worker pid {victim.pid} at "
                    f"{used:.0%} used", node_id=self.node_id.hex(),
                    worker_id=victim.worker_id.hex(), pid=victim.pid)
                self._on_worker_dead(
                    victim, f"killed by memory monitor at "
                            f"{used:.0%} used")
            except Exception:
                logger.exception("memory monitor iteration failed")

    def _forget_worker_logs(self, pid: int) -> None:
        for path in [p for p, wpid in self._log_pids.items()
                     if wpid == pid]:
            self._log_pids.pop(path, None)
            self._log_offsets.pop(path, None)

    @staticmethod
    def _scan_worker_logs(snapshot):
        """Read new complete lines from worker log files.  Sync —
        ``_log_monitor_loop`` runs it in an executor because a tick can
        read up to 1 MiB per file off a cold page cache, which must not
        stall the raylet's event loop (leases, pulls, heartbeats).
        Takes ``[(path, pid, offset)]``; returns ``(batch, offsets)``
        with only the offsets that advanced."""
        batch: List[Dict[str, Any]] = []
        offsets: Dict[str, int] = {}
        for path, pid, offset in snapshot:
            try:
                size = os.path.getsize(path)
                if size <= offset:
                    continue
                with open(path, "rb") as f:
                    f.seek(offset)
                    chunk = f.read(min(size - offset, 1 << 20))
            except OSError:
                # file vanished/unreadable mid-scan (worker reaped):
                # skip it, keep the rest of the tick's batch
                continue
            # only complete lines; partial tail re-read next
            # tick.  A single line longer than the read window
            # would never complete — force-flush so the offset
            # always advances.
            cut = chunk.rfind(b"\n")
            if cut < 0:
                if len(chunk) < (1 << 20):
                    continue
                cut = len(chunk) - 1
            offsets[path] = offset + cut + 1
            lines = chunk[:cut + 1].decode(errors="replace").splitlines()
            if lines:
                batch.append({"pid": pid,
                              "is_err": path.endswith(".err"),
                              "lines": lines})
        return batch, offsets

    async def _log_monitor_loop(self) -> None:
        """Tail worker stdout/stderr files and publish new lines to the
        GCS so drivers can echo them (parity: log_monitor.py:100 ->
        pubsub -> driver '(pid=...)' prefixes)."""
        loop = asyncio.get_running_loop()
        while not self._closing:
            await asyncio.sleep(0.5)
            try:
                snapshot = [(path, pid, self._log_offsets.get(path, 0))
                            for path, pid in self._log_pids.items()]
                batch, offsets = await loop.run_in_executor(
                    None, self._scan_worker_logs, snapshot)
                for path, offset in offsets.items():
                    # a worker reaped mid-scan must stay forgotten
                    if path in self._log_pids:
                        self._log_offsets[path] = offset
                if batch and self.gcs_conn and not self.gcs_conn.closed:
                    await self.gcs_conn.call("publish", {
                        "channel": "worker_logs",
                        "message": {
                            "node_id": self.node_id.hex()[:8],
                            "records": batch,
                        }})
            except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError):
                pass
            except Exception:
                logger.exception("log monitor iteration failed")

    async def _reap_loop(self) -> None:
        """Detect dead worker processes (parity: WorkerPool SIGCHLD path)."""
        while not self._closing:
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None:
                    self._on_worker_dead(w, f"exit code {w.proc.returncode}")
            # workers that died before registering (startup crash)
            for entry in list(self._spawned_procs):
                proc, token = entry[0], entry[3]
                if proc.poll() is not None:
                    self._spawned_procs.remove(entry)
                    self._dec_starting(entry[2])
                    env_hash = self._env_spawn_hash.get(token) \
                        if token else None
                    self._dec_starting_env(token)
                    if env_hash is not None:
                        # an isolated-env worker that dies at boot will
                        # keep dying — break the env instead of hot-
                        # looping spawns; leases fail with this message
                        msg = (f"isolated runtime env worker exited "
                               f"{proc.returncode} at startup (see "
                               f"worker logs in {self.session_dir}"
                               f"/logs)")
                        self._env_broken[env_hash] = msg
                        asyncio.get_running_loop().call_later(
                            30.0,
                            lambda h=env_hash:
                            self._env_broken.pop(h, None))
                    logger.warning("worker pid %d died before registering "
                                   "(exit %d)", proc.pid, proc.returncode)
                    self._maybe_schedule()
            # trim the idle pool back to the prestart watermark: demand
            # from many distinct clients can grow it past the per-core
            # cap (see cap_bonus in _maybe_schedule); workers idle >10 s
            # are surplus
            target = self._pool_target()
            now = time.monotonic()
            # env-bound workers get a much longer grace (their
            # interpreter IS the runtime env; a respawn replays the
            # whole env build) but are not exempt — exemption leaked
            # one interpreter per distinct env forever
            while len(self._idle) > target and self._cull_idle_spare(
                    lambda w: now - w.idle_since >
                    (300.0 if w.env_hash is not None else 10.0)):
                pass
            # safety re-kick: if demand is queued with nothing idle and
            # no retry timer armed (e.g. _maybe_schedule ran without a
            # loop), rescan so waiting leases can't stall indefinitely
            if self._fair.pending_count() and not self._idle \
                    and not self._reclaim_timer_armed:
                self._maybe_schedule()
            # demand-driven pool rebuild, only while the lease plane is
            # QUIET (spawn storms during an active wave steal the CPU
            # the wave itself needs) and rate-limited per tick
            # (warm_pool_rebuild_per_tick): the next actor wave then
            # lands on warm forks.  Counted against PLAIN idle workers —
            # idle env workers can't serve ordinary leases and must not
            # suppress the rebuild.
            if not self._fair.pending_count() and not self._closing and \
                    not self._creating_actors and \
                    now - getattr(self, "_last_lease_ts", 0.0) > 1.5:
                idle_plain = sum(1 for w in self._idle
                                 if w.env_hash is None)
                deficit = target - idle_plain - self._starting
                bonus = max(0, target - self._max_workers)
                per_tick = max(1, int(getattr(
                    self.config, "warm_pool_rebuild_per_tick", 4)))
                for _ in range(min(per_tick, deficit)):
                    if not self._start_worker(None, cap_bonus=bonus):
                        break
            self._maybe_spill_ahead()
            await asyncio.sleep(0.2)

    def _maybe_spill_ahead(self) -> None:
        """Async spill-AHEAD (ROADMAP item 2 remainder): when arena use
        crosses ``object_spill_ahead_watermark`` — a line BELOW the
        create-path spill threshold — kick one background sweep that
        spills cold sealed primaries back toward the watermark, off the
        critical path.  A later pressure burst (streaming shuffle
        intermediates, bursty puts) then finds headroom instead of
        paying blob-write latency inside ``put()``.  One sweep at a
        time; it shares ``_spill_lock`` with the reactive path, so the
        two can never double-spill."""
        wm = float(getattr(self.config, "object_spill_ahead_watermark",
                           0.0) or 0.0)
        if wm <= 0 or self._closing or self._spill_ahead_running:
            return
        target = wm * self.store_capacity
        if self.store.used() <= target:
            return
        self._spill_ahead_running = True
        task = asyncio.get_running_loop().create_task(
            self._spill_ahead_sweep(target))
        task.add_done_callback(lambda t: t.exception())

    async def _spill_ahead_sweep(self, target: float) -> None:
        try:
            if self._spill_lock is None:
                self._spill_lock = asyncio.Lock()
            async with self._spill_lock:
                used = self.store.used()
                if used > target:
                    await self._spill_sweep(int(used - target))
        except Exception:  # noqa: BLE001 — ahead-of-time work only;
            # the reactive create-path sweep still guards correctness
            logger.exception("spill-ahead sweep failed")
        finally:
            self._spill_ahead_running = False

    # ------------------------------------------------------------------
    # worker pool
    # ------------------------------------------------------------------
    def _start_worker(self, job_id_bin: Optional[bytes],
                      needs_tpu: bool = False, cap_bonus: int = 0) -> bool:
        """Returns False when the pool cap declines the spawn.

        ``cap_bonus`` lets demand from DISTINCT clients grow the pool past
        the per-core cap: leases are exclusive per client, so on a
        low-core host N concurrent clients would otherwise serialize
        behind worker handoffs even for CPU:0 work (the 1->8-client
        scaling collapse).  Bounded in _maybe_schedule.
        """
        # the cap bounds the *task pool*; workers holding actors live
        # outside it (parity: reference WorkerPool — actor workers are
        # dedicated, else a few CPU:0 actors starve all task execution)
        pool_size = self._starting + sum(
            1 for w in self.workers.values() if not w.is_actor)
        if pool_size >= self._max_workers + cap_bonus:
            return False
        self._starting += 1
        if needs_tpu:
            self._starting_tpu += 1
        env = dict(os.environ)
        env["RAY_TPU_WORKER"] = "1"
        # The accelerator plugin env travels via the node daemon's stash
        # (node.py _spawn strips it from daemons so they stay jax-free);
        # raylets started outside node.py carry it directly.
        pool_ips = env.pop("RAY_TPU_STASH_AXON_POOL_IPS", None) \
            or env.pop("PALLAS_AXON_POOL_IPS", None)
        jax_platforms = env.pop("RAY_TPU_STASH_JAX_PLATFORMS", None)
        tpu_capable = True
        if pool_ips:
            if needs_tpu:
                # TPU workers pay the accelerator-plugin sitecustomize
                # (~2s jax import) and get the original backend selection
                env["PALLAS_AXON_POOL_IPS"] = pool_ips
                if jax_platforms:
                    env["JAX_PLATFORMS"] = jax_platforms
                else:
                    env.pop("JAX_PLATFORMS", None)
            else:
                # plain pool workers skip it; JAX_PLATFORMS stays cpu
                tpu_capable = False
        log_base = os.path.join(self.session_dir, "logs",
                                f"worker-{os.getpid()}-{self._starting}-{time.monotonic_ns()}")
        os.makedirs(os.path.dirname(log_base), exist_ok=True)
        worker_args = [
            "--raylet", f"{self.server.address[0]}:{self.server.address[1]}",
            "--gcs", f"{self.gcs_address[0]}:{self.gcs_address[1]}",
            "--node-id", self.node_id.hex(),
            "--store-path", self.store.path,
            "--store-capacity", str(self.store_capacity),
            "--session-dir", self.session_dir,
        ]
        if job_id_bin is not None:
            worker_args += ["--job-id", job_id_bin.hex()]
        if not needs_tpu and time.monotonic() >= getattr(
                self, "_zygote_broken_until", 0.0):
            # fork from the warm zygote (~10 ms) instead of a cold
            # interpreter (~300 ms) — actor-creation rate on many-core
            # hosts is bounded by this.  Forked workers stay TPU-capable
            # unless the host uses an import-time accelerator plugin
            # (sitecustomize only runs at real interpreter start).
            self._spawn_via_zygote(worker_args, log_base, tpu_capable,
                                   env, needs_tpu)
            return True
        self._spawn_cold(worker_args, log_base, env, tpu_capable, needs_tpu)
        return True

    def _spawn_cold(self, worker_args, log_base: str, env: Dict[str, str],
                    tpu_capable: bool, needs_tpu: bool = False) -> None:
        cmd = [sys.executable, "-m", "ray_tpu.core.worker_main",
               *worker_args]
        out = open(log_base + ".out", "ab")
        err = open(log_base + ".err", "ab")
        from ray_tpu.core.node import safe_die_with_parent

        # workers die with their raylet (a worker without its raylet is
        # unreachable; reference workers exit on raylet death).  The
        # raylet loop runs on the process main thread, so the PDEATHSIG
        # thread caveat doesn't bite; gate anyway for exotic embeddings.
        # Armed child-side (worker_main) so Popen stays preexec_fn-free
        # and takes the posix_spawn path — a TPU-hosting raylet has jax
        # threads running, and forking those is the latent-deadlock class.
        if safe_die_with_parent():
            env["RAY_TPU_PDEATHSIG"] = str(os.getpid())
        proc = subprocess.Popen(
            cmd, env=env, stdout=out, stderr=err, close_fds=False)
        # log monitor maps these files to the worker pid for prefixes
        self._log_pids[log_base + ".out"] = proc.pid
        self._log_pids[log_base + ".err"] = proc.pid
        # handle registered later in handle_register_worker; remember proc
        self._spawned_procs.append((proc, tpu_capable, needs_tpu, None))

    def _start_env_worker(self, lease: "PendingLease") -> None:
        """Spawn a worker under an isolated runtime env (venv / conda /
        container / py_executable).  The env build (pip install, conda
        create, image pull) can take seconds-to-minutes, so it runs in
        the default executor; the io loop only does bookkeeping.
        Isolated workers register pre-bound to their env_hash and never
        serve other envs."""
        env_hash, env_spawn = lease.env_hash, dict(lease.env_spawn)
        # same cap formula as _start_worker (idle workers are already in
        # self.workers — counting them twice would stall at half cap)
        pool_size = self._starting + sum(
            1 for w in self.workers.values() if not w.is_actor)
        if pool_size >= self._max_workers:
            # make room, else the lease waits for pool churn
            if not self._cull_idle_spare(lambda w: w.env_hash is None):
                return
        token = f"env-{env_hash}-{time.monotonic_ns()}"
        self._starting += 1
        self._starting_env[env_hash] = \
            self._starting_env.get(env_hash, 0) + 1
        env = dict(os.environ)
        env["RAY_TPU_WORKER"] = "1"
        env["RAY_TPU_WORKER_ENV_HASH"] = env_hash
        env["RAY_TPU_WORKER_SPAWN_TOKEN"] = token
        # isolated interpreters may not have ray_tpu on their default
        # path (venv --system-site-packages does; conda/container need
        # the package root)
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        env.pop("RAY_TPU_STASH_AXON_POOL_IPS", None)
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("RAY_TPU_STASH_JAX_PLATFORMS", None)
        log_base = os.path.join(
            self.session_dir, "logs",
            f"worker-{os.getpid()}-{self._starting}-{time.monotonic_ns()}")
        os.makedirs(os.path.dirname(log_base), exist_ok=True)
        worker_args = [
            "--raylet",
            f"{self.server.address[0]}:{self.server.address[1]}",
            "--gcs", f"{self.gcs_address[0]}:{self.gcs_address[1]}",
            "--node-id", self.node_id.hex(),
            "--store-path", self.store.path,
            "--store-capacity", str(self.store_capacity),
            "--session-dir", self.session_dir,
        ]
        if lease.job_id_bin is not None:
            worker_args += ["--job-id", lease.job_id_bin.hex()]
        from ray_tpu.core.node import safe_die_with_parent

        if safe_die_with_parent():
            env["RAY_TPU_PDEATHSIG"] = str(os.getpid())
        loop = asyncio.get_running_loop()

        def build_and_spawn():
            from ray_tpu import runtime_env as renv

            cmd = renv.resolve_worker_command(
                env_spawn,
                [sys.executable, "-m", "ray_tpu.core.worker_main",
                 *worker_args],
                mounts=[self.session_dir],
                passthrough_env={
                    "RAY_TPU_WORKER": "1",
                    "RAY_TPU_WORKER_ENV_HASH": env_hash,
                    "RAY_TPU_WORKER_SPAWN_TOKEN": token,
                })
            out = open(log_base + ".out", "ab")
            err = open(log_base + ".err", "ab")
            return subprocess.Popen(cmd, env=env, stdout=out,
                                    stderr=err, close_fds=False)

        fut = loop.run_in_executor(None, build_and_spawn)

        def _done(f):
            try:
                proc = f.result()
            except Exception as e:  # noqa: BLE001 — report to leases
                logger.exception("isolated runtime env %s build/spawn "
                                 "failed", env_hash)
                msg = f"runtime env build failed: {e}"
                self._env_broken[env_hash] = msg
                # transient causes (network, registry) deserve a retry
                loop.call_later(
                    30.0, lambda: self._env_broken.pop(env_hash, None))
                self._starting -= 1
                self._starting_env[env_hash] -= 1
                self._maybe_schedule()  # fails the waiting leases
                return
            self._log_pids[log_base + ".out"] = proc.pid
            self._log_pids[log_base + ".err"] = proc.pid
            self._env_spawn_hash[token] = env_hash
            self._spawned_procs.append((proc, False, False, token))

        fut.add_done_callback(_done)

    def _spawn_via_zygote(self, worker_args, log_base: str,
                          tpu_capable: bool, env: Dict[str, str],
                          needs_tpu: bool = False) -> None:
        if getattr(self, "_zygote", None) is None:
            self._zygote = _ZygoteClient(self.session_dir)
        loop = asyncio.get_running_loop()
        zygote = self._zygote

        def _fork():
            # failpoint: the zygote fork fails — the raylet must fall
            # back to a cold spawn and back off the fork path for a
            # while, never wedge the lease that wanted the worker
            _fp.failpoint("raylet.zygote.fork_fail")
            return zygote.spawn(worker_args, {"RAY_TPU_WORKER": "1"},
                                log_base)

        fut = loop.run_in_executor(None, _fork)

        def _done(f):
            try:
                pid = f.result()
            except Exception:
                # broken zygote: cold-spawn this worker now and stop
                # using the fork path for a while (a hot retry loop
                # would pay a failed ~300ms zygote start per lease)
                logger.exception(
                    "zygote spawn failed; cold-spawning and backing off")
                self._zygote_broken_until = time.monotonic() + 30.0
                try:
                    self._zygote.stop()
                except Exception:
                    pass
                self._zygote = None
                self._spawn_cold(worker_args, log_base, env, tpu_capable,
                                 needs_tpu)
                return
            handle = _ForkedProc(pid)
            self._log_pids[log_base + ".out"] = pid
            self._log_pids[log_base + ".err"] = pid
            # the child usually registers AFTER this callback (it must
            # finish CoreWorker init first), but adopt either ordering
            for worker in self.workers.values():
                if worker.pid == pid and worker.proc is None:
                    worker.proc = handle
                    worker.tpu_capable = tpu_capable
                    self._dec_starting(needs_tpu)
                    self._maybe_schedule()  # freed pool capacity
                    return
            self._spawned_procs.append((handle, tpu_capable, needs_tpu, None))

        fut.add_done_callback(_done)

    async def handle_register_worker(self, conn, data):
        if data.get("is_driver"):
            # drivers use the object plane but never join the worker pool
            conn.context["is_driver"] = True
            return {"node_id": self.node_id.binary(),
                    "config": self.config.to_json(),
                    "profiler": self._profiler_handoff()}
        wid = WorkerID(data["worker_id"])
        existing = self.workers.get(wid)
        if existing is not None and existing.conn is conn:
            # replayed registration (the pool retries register_worker
            # after a lost ack): the first delivery already adopted the
            # spawn handle, decremented _starting, and pooled the
            # worker — pooling it into _idle AGAIN would double-lease
            # it, so just re-serve the ack
            return {"node_id": self.node_id.binary(),
                    "config": self.config.to_json(),
                    "profiler": self._profiler_handoff()}
        worker = WorkerHandle(
            worker_id=wid,
            pid=data["pid"],
            job_id_bin=data.get("job_id"),
            conn=conn,
            task_address=tuple(data["task_address"]),
        )
        # adopt the spawned process handle: spawn token first (container
        # workers register with a namespaced pid), host pid otherwise
        reg_token = data.get("spawn_token")
        for entry in list(self._spawned_procs):
            proc, tpu_capable, was_tpu_spawn, token = entry
            # with a spawn token, match on it EXCLUSIVELY: a container
            # worker's namespaced pid can collide with an unrelated
            # pending proc entry, mis-adopting the handle and corrupting
            # the _starting accounting
            if (token == reg_token) if reg_token is not None \
                    else (proc.pid == worker.pid):
                worker.proc = proc
                worker.tpu_capable = tpu_capable
                self._spawned_procs.remove(entry)
                self._dec_starting(was_tpu_spawn)
                break
        # isolated-env workers are born bound to their env
        env_hash = data.get("env_hash") \
            or (self._env_spawn_hash.get(reg_token) if reg_token else None)
        if env_hash is not None:
            worker.env_hash = env_hash
            worker.tpu_capable = False
        self._dec_starting_env(reg_token)
        conn.context["worker_id"] = worker.worker_id
        self.workers[worker.worker_id] = worker
        worker.idle_since = time.monotonic()
        self._idle.append(worker)
        self._maybe_schedule()
        return {"node_id": self.node_id.binary(),
                "config": self.config.to_json(),
                "profiler": self._profiler_handoff()}

    def _profiler_handoff(self) -> Optional[Dict[str, Any]]:
        """Profiler state for a registering worker: the remaining slice
        of an in-progress window, or None when not profiling."""
        state = self._profiler_state
        if not state or not state.get("enabled"):
            return None
        deadline = state.get("deadline")
        remaining = None
        if deadline is not None:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self._profiler_state = None
                return None
        return {"enabled": True, "hz": state.get("hz"),
                "remaining_s": remaining}

    async def handle_profiler_control(self, conn, data):
        """Apply a cluster profiling window to this node: the raylet's
        own sampler plus a best-effort fan-out to every live worker
        (dead/wedged workers are exactly what the profile should not
        block on)."""
        enabled = bool(data["enabled"])
        hz = data.get("hz")
        duration = data.get("duration_s")
        _prof.configure(enabled, hz=hz, duration_s=duration)
        self._profiler_state = {
            "enabled": enabled, "hz": hz,
            "deadline": (time.monotonic() + float(duration)
                         if enabled and duration else None),
        } if enabled else None

        async def one(conn2):
            try:
                await asyncio.wait_for(
                    conn2.call("profiler_control", data), 5.0)
                return True
            except Exception:  # noqa: BLE001 — best effort
                return False

        # workers by handle, plus DRIVER registration conns (drivers
        # never join the pool, but a training driver's loop is often
        # exactly the thing worth sampling)
        targets = [w.conn for w in self.workers.values()]
        targets += [c for c in self.server.connections
                    if c.context.get("is_driver") and not c.closed]
        results = await asyncio.gather(*(one(c) for c in targets))
        return {"node_id": self.node_id.hex(),
                "workers_applied": sum(1 for r in results if r),
                "workers_total": len(results)}

    def on_disconnection(self, conn) -> None:
        # release transfer pins a crashed/vanished puller left behind —
        # without this a dead puller pinned this node's copies forever
        # (they could never be evicted or spilled)
        for oid in conn.context.pop("pull_leases", set()):
            try:
                self.store.release(oid)
            except Exception:  # noqa: BLE001 — store may be closing
                pass
        conn.context.pop("pull_offsets", None)
        # close spill-file serves a dead puller left open (the fd pins
        # the blob's inode against owner-free unlinks)
        for fd, _size in conn.context.pop("spill_serves", {}).values():
            try:
                os.close(fd)
            except OSError:
                pass
        worker_id = conn.context.get("worker_id")
        if worker_id is not None:
            w = self.workers.get(worker_id)
            if w is not None:
                self._on_worker_dead(w, "connection lost")

    def _on_worker_dead(self, worker: WorkerHandle, reason: str) -> None:
        self.workers.pop(worker.worker_id, None)
        # stop tailing the dead worker's logs after one more tick (which
        # drains any final lines)
        try:
            asyncio.get_event_loop().call_later(
                2.0, self._forget_worker_logs, worker.pid)
        except RuntimeError:
            self._forget_worker_logs(worker.pid)
        if worker in self._idle:
            self._idle.remove(worker)
        if worker.leased:
            self._release_lease_resources(worker)
        logger.info("worker %s (pid %d) dead: %s",
                    worker.worker_id.hex()[:12], worker.pid, reason)
        _flight.record("worker_dead",
                       f"pid={worker.pid} "
                       f"wid={worker.worker_id.hex()[:12]} {reason}")
        # forensics: ship the dead worker's flight-ring tail to the GCS
        # incident journal.  A gracefully-exiting worker unlinks its own
        # ring (CoreWorker.shutdown), so a surviving ring for a dead pid
        # means a crash; runtime-intended kills (PG bundle revoke,
        # raylet shutdown) are excluded explicitly.
        if not self._closing \
                and reason != "placement group bundle returned":
            self._ship_flight_tail(worker.pid, reason)
        self._maybe_schedule()

    def _ship_flight_tail(self, pid: int, reason: str) -> None:
        """Read the flight ring a dead process left in the session dir
        and fire-and-forget it to the GCS death-notification path.
        Best-effort by design: a missing/foreign ring or a dropped RPC
        degrades the incident to partial, never blocks worker reaping."""
        tails = []
        for path in _flight.rings_for_pid(self.session_dir, pid):
            tail = _flight.read_ring(path)
            if tail is not None:
                tails.append(tail)
            try:
                os.unlink(path)  # dead pid: nobody writes this again
            except OSError:
                pass
        if not tails or self.gcs_conn is None or self.gcs_conn.closed:
            return

        async def _ship():
            for tail in tails:
                try:
                    await self.gcs_conn.call("report_flight_tail", {
                        "source": tail["source"], "pid": pid,
                        "node_id": self.node_id.binary(),
                        "reason": reason, "torn": tail["torn"],
                        "frames": tail["frames"][-200:],
                    }, timeout=5.0)
                except (rpc.ConnectionLost, rpc.RpcError,
                        asyncio.TimeoutError, OSError):
                    pass  # incident opens partial from the death event

        try:
            t = asyncio.get_event_loop().create_task(_ship())
            t.add_done_callback(lambda t: t.exception())
        except RuntimeError:
            pass

    # ------------------------------------------------------------------
    # resource accounting
    # ------------------------------------------------------------------
    def _resource_pool(self, bundle: Optional[Tuple[bytes, int]]
                       ) -> Dict[str, float]:
        if bundle is not None:
            return self._bundles.get(bundle, {})
        return self.resources_available

    def _fits(self, resources: Dict[str, float],
              bundle: Optional[Tuple[bytes, int]]) -> bool:
        pool = self._resource_pool(bundle)
        return all(pool.get(k, 0.0) >= v for k, v in resources.items())

    def _feasible_ever(self, resources: Dict[str, float],
                       bundle: Optional[Tuple[bytes, int]]) -> bool:
        if bundle is not None:
            pool = self._bundle_totals.get(bundle)
            if pool is None:
                return False
            return all(pool.get(k, 0.0) >= v for k, v in resources.items())
        return all(self.resources_total.get(k, 0.0) >= v
                   for k, v in resources.items())

    def _take(self, resources: Dict[str, float],
              bundle: Optional[Tuple[bytes, int]]) -> None:
        pool = self._resource_pool(bundle)
        for k, v in resources.items():
            pool[k] = pool.get(k, 0.0) - v

    def _give(self, resources: Dict[str, float],
              bundle: Optional[Tuple[bytes, int]]) -> None:
        if bundle is not None and bundle not in self._bundles:
            # bundle was returned while this lease was out: return_bundle
            # refunded only the unleased remainder, so the leased share
            # re-enters the node pool here
            pool = self.resources_available
        else:
            pool = self._resource_pool(bundle)
        for k, v in resources.items():
            pool[k] = pool.get(k, 0.0) + v

    def _utilization(self) -> float:
        fractions = []
        for k, total in self.resources_total.items():
            if total > 0:
                fractions.append(
                    1.0 - self.resources_available.get(k, 0.0) / total)
        return max(fractions) if fractions else 0.0

    # ------------------------------------------------------------------
    # lease scheduling (ClusterTaskManager + LocalTaskManager)
    # ------------------------------------------------------------------
    async def handle_request_worker_lease(self, conn, data):
        """Returns {granted, worker_address, lease_id} | {spillback: addr} —
        or blocks (queues) until a local grant is possible."""
        # failpoint: a slow/failed lease grant — owners must keep their
        # backlog intact (freeze or redispatch), never burn retry budget
        # on a raylet that is merely late
        await _fp.afailpoint("raylet.lease_grant.delay")
        resources = dict(data.get("resources", {}))
        bundle = None
        pg_bin = data.get("placement_group_id")
        if pg_bin is not None:
            bundle = (pg_bin, data.get("bundle_index", -1))
            bundle = self._resolve_bundle(bundle, resources)
            if bundle is None:
                return {"error": "placement group bundle not on this node"}
        job_id_bin = data.get("job_id")
        job_key = job_id_bin.hex() if job_id_bin else f"conn-{id(conn):x}"

        if self._draining:
            # a draining node takes no new work: hand the request to an
            # ACTIVE peer outright.  Pinned demand (placement groups /
            # NODE_AFFINITY) queues — the drain's re-spill pass aborts
            # the drain if it cannot move, so the request never fails.
            spill = self._pick_spillback(resources, data,
                                         force_remote=True)
            if spill is not None:
                return {"spillback": spill}
        elif not self._fits(resources, bundle):
            spill = self._pick_spillback(resources, data)
            if spill is not None:
                return {"spillback": spill}
            if bundle is None and not self._feasible_ever(resources, None) \
                    and not self._feasible_anywhere(resources):
                logger.warning(
                    "lease demand %s infeasible cluster-wide; queueing "
                    "(waiting for new nodes)", resources)
        self._last_lease_ts = time.monotonic()
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        lease = PendingLease(
            request=data, future=fut, job_id_bin=job_id_bin,
            resources=resources, bundle=bundle,
            env_hash=data.get("env_hash"),
            env_spawn=data.get("env_spawn"),
            retriable=bool(data.get("retriable", True)),
            token=data.get("token"), conn=conn, job_key=job_key)
        try:
            self._fair.push(lease, job_key)
        except QuotaExceeded as e:
            # reject-mode tenant past its in-flight ceiling: bounce at
            # admission (the queue-mode alternative parks instead)
            return {"error": str(e), "quota_rejected": True}
        self._maybe_schedule()
        # traced lease (the owner forwarded its head task's context):
        # the queue-wait-until-grant hop joins the request's trace tree
        lease_span = _trace.start_span("raylet.lease",
                                       node=self.node_id.hex()[:12])
        if lease_span is None:
            return await fut
        try:
            result = await fut
        except BaseException:
            # owner conn dropped / dispatch cancelled: the queue-wait
            # hop must still land — a lost span would hide exactly the
            # slow-lease case it exists to explain
            lease_span.end(status="error")
            raise
        lease_span.end(granted=bool(result.get("granted"))
                       if isinstance(result, dict) else False)
        return result

    async def handle_cancel_lease(self, conn, data):
        """The owner's backlog drained before the grant: drop the queued
        request so a later grant doesn't churn a worker through a
        grant->instant-return cycle while real demand waits."""
        token = data.get("token")
        if token is None:
            return False
        for lease in self._fair.pending():
            if lease.token == token:
                self._fair.remove(lease)
                if not lease.future.done():
                    lease.future.set_result({"canceled": True})
                return True
        return False

    def _resolve_bundle(self, bundle: Tuple[bytes, int],
                        resources: Dict[str, float]
                        ) -> Optional[Tuple[bytes, int]]:
        if bundle[1] >= 0:
            return bundle if bundle in self._bundles else None
        # bundle_index == -1: any committed bundle of the group that fits
        for key in self._bundles:
            if key[0] == bundle[0]:
                pool = self._bundles[key]
                if all(pool.get(k, 0.0) >= v for k, v in resources.items()):
                    return key
        # fall back to any bundle of the group (will queue)
        for key in self._bundles:
            if key[0] == bundle[0]:
                return key
        return None

    def _feasible_anywhere(self, resources: Dict[str, float]) -> bool:
        for node in self._cluster_view:
            if not node.get("alive") \
                    or node.get("state", NODE_ACTIVE) != NODE_ACTIVE:
                continue
            total = node.get("resources_total", {})
            if all(total.get(k, 0.0) >= v for k, v in resources.items()):
                return True
        return all(self.resources_total.get(k, 0.0) >= v
                   for k, v in resources.items())

    def _pick_spillback(self, resources: Dict[str, float],
                        data: Dict[str, Any],
                        force_remote: bool = False
                        ) -> Optional[rpc.Address]:
        """Hybrid policy: if local is saturated, hand the lease to the
        least-loaded remote node that can run it *now*.  With
        ``force_remote`` (this node is draining) staying local is not
        an option: any ACTIVE peer that could EVER run the shape takes
        it — the lease may queue there, but it never strands on a node
        about to release."""
        strategy = data.get("strategy", "DEFAULT")
        if strategy == "NODE_AFFINITY" or data.get("placement_group_id"):
            return None  # pinned to this node
        remotes = [n for n in self._cluster_view
                   if n.get("alive")
                   and n.get("state", NODE_ACTIVE) == NODE_ACTIVE
                   and bytes(n["node_id"]) != self.node_id.binary()]
        if not remotes:
            return None
        # broadcast load is up to one sync period stale: every spill in
        # that window would pile onto the same "least loaded" node.
        # Charge each spill decision locally with exponential decay
        # (half-life = one sync period, when fresh broadcasts fold the
        # real load back in) so consecutive spills fan out without
        # double-counting for long (parity: the reference tracks its own
        # backlog per node between resource-view updates).
        now = time.monotonic()
        pressure = self._spill_pressure
        half_life = self.config.resource_broadcast_period_s

        def decayed_count(key) -> float:
            entry = pressure.get(key)
            if entry is None:
                return 0.0
            count, ts = entry
            value = count * 0.5 ** ((now - ts) / half_life)
            if value < 0.05:  # expired: drop so dead nodes don't pile up
                del pressure[key]
                return 0.0
            return value

        def charged_load(node) -> float:
            return node.get("load", 0) + decayed_count(
                bytes(node["node_id"]))

        def charge(node) -> None:
            key = bytes(node["node_id"])
            pressure[key] = (decayed_count(key) + 1.0, now)

        if force_remote:
            # feasible-by-TOTAL, least charged load: instant
            # availability is the wrong bar when the alternative is a
            # lease stranded on a draining node
            best = None
            best_load = None
            for node in remotes:
                total = node.get("resources_total", {})
                if all(total.get(k, 0.0) >= v
                       for k, v in resources.items()):
                    load = charged_load(node)
                    if best is None or load < best_load:
                        best, best_load = node, load
            if best is None:
                return None
            charge(best)
            return tuple(best["address"])

        try:
            # the hybrid/spread decision runs in the native scheduling
            # core (src/sched_core.cc — the reference's
            # ClusterResourceScheduler/hybrid policy is C++ too)
            from ray_tpu.core import native

            idx = native.sched_pick_node(
                [(n.get("resources_available", {}), charged_load(n))
                 for n in remotes],
                resources,
                strategy=strategy,
                local_utilization=self._utilization(),
                spread_threshold=self.config.scheduler_spread_threshold,
                local_feasible=self._feasible_ever(resources, None))
            if idx is None:
                return None
            charge(remotes[idx])
            return tuple(remotes[idx]["address"])
        except OSError:  # toolchain unavailable: python fallback
            pass
        best = None
        best_load = None
        for node in remotes:
            avail = node.get("resources_available", {})
            if all(avail.get(k, 0.0) >= v for k, v in resources.items()):
                load = charged_load(node)
                if best is None or load < best_load:
                    best, best_load = node, load
        if best is None:
            return None
        if strategy == "SPREAD":
            charge(best)
            return tuple(best["address"])
        # hybrid: stay local while below the spread threshold and feasible
        if self._utilization() < self.config.scheduler_spread_threshold and \
                self._feasible_ever(resources, None):
            return None
        charge(best)
        return tuple(best["address"])

    def _maybe_schedule(self) -> None:
        """Grant queued leases in weighted deficit-round-robin order —
        per-job sub-queues with quota ceilings (FairQueue); job-less
        leases key by client connection, so the multi-client interleave
        degenerates to the pre-quota round-robin.  Spills queued leases
        to other nodes as the cluster view evolves."""
        if self._closing or self._sched_suspended:
            return
        # pre-pass: drop settled futures; re-evaluate spillback for
        # leases this node can't fit (e.g. demand for a resource this
        # node will never have) — and, while draining, for EVERY lease
        for lease in self._fair.pending():
            if lease.future.done():
                self._fair.remove(lease)
                continue
            if self._draining or not self._fits(lease.resources,
                                                lease.bundle):
                if lease.bundle is None:
                    spill = self._pick_spillback(
                        lease.resources, lease.request,
                        force_remote=self._draining)
                    if spill is not None:
                        self._fair.remove(lease)
                        lease.future.set_result({"spillback": spill})
        if self._draining:
            # a draining node grants nothing: leases that could not
            # spill stay queued — the drain either re-spills them
            # before DRAINED or aborts back to ACTIVE and re-runs this
            self._note_backlog_demand(self._fair.pending_count())
            return
        want_workers: List[Tuple[Optional[bytes], bool, int]] = []
        wanted: Set[int] = set()  # fits() may probe one lease per round
        errors: Dict[int, Tuple[PendingLease, str]] = {}

        def fits(lease: PendingLease) -> bool:
            """Feasibility probe for one grant attempt: resources AND a
            worker.  On success the popped worker rides the lease to
            the commit loop below (same synchronous pass — nothing can
            interleave)."""
            if id(lease) in errors \
                    or not self._fits(lease.resources, lease.bundle):
                return False
            needs_tpu = lease.resources.get("TPU", 0) > 0
            # isolated envs live in the worker's interpreter itself, so
            # only a worker born under that env can serve the lease —
            # pristine pool workers are no substitute
            worker = self._pop_idle(lease.job_id_bin, needs_tpu,
                                    lease.env_hash,
                                    exact_env_only=lease.env_spawn
                                    is not None)
            if worker is None:
                if not lease.pool_missed:
                    lease.pool_missed = True
                    _tm.sched_warm_pool(False)
                if lease.env_spawn is not None \
                        and lease.env_hash is not None:
                    # isolated env: the worker must be BORN under the
                    # env's interpreter/container — spawn dedicated
                    if needs_tpu:
                        errors[id(lease)] = (lease,
                            "isolated runtime envs (venv/conda/"
                            "container/py_executable) cannot lease "
                            "TPUs; use the in-process pip env for "
                            "TPU tasks")
                    elif self._env_broken.get(lease.env_hash) is not None:
                        errors[id(lease)] = (
                            lease, self._env_broken[lease.env_hash])
                    elif self._starting_env.get(lease.env_hash, 0) == 0:
                        self._start_env_worker(lease)
                    return False
                if id(lease) not in wanted:
                    wanted.add(id(lease))
                    want_workers.append((lease.job_id_bin, needs_tpu,
                                         id(lease.conn)))
                return False
            lease.granted_worker = worker
            return True

        fair_grants = self._fair.grant_order(fits)
        for lease, err in errors.values():
            self._fair.remove(lease)
            if not lease.future.done():
                lease.future.set_result({"error": err})
        grants: List[Tuple[PendingLease, WorkerHandle]] = []
        for job_key, lease in fair_grants:
            worker, lease.granted_worker = lease.granted_worker, None
            self._take(lease.resources, lease.bundle)
            _tm.lease_granted(time.monotonic() - lease.enqueued_at)
            if not lease.pool_missed:
                _tm.sched_warm_pool(True)
            worker.leased = True
            worker.lease_resources = lease.resources
            worker.lease_bundle = lease.bundle
            worker.lease_retriable = lease.retriable
            worker.lease_granted_at = time.monotonic()
            worker.lease_token = lease.token
            worker.lease_job_key = job_key
            worker.owner_conn = lease.conn
            if lease.env_hash is not None:
                worker.env_hash = lease.env_hash
            self._assign_tpu_ids(worker, lease.resources.get("TPU", 0.0))
            if _flight.enabled():
                _flight.record("lease_grant",
                               f"pid={worker.pid} "
                               f"res={lease.resources} "
                               f"job={job_key}")
            grants.append((lease, worker))
        remaining = self._fair.pending()
        # Grants resolve AFTER the pass so each reply can carry an exact
        # contention signal: demand is still queued, so the owner should
        # hand the worker back the moment it idles instead of holding it
        # through the idle-lease grace (the grace exists for lease reuse
        # on sync-style submit patterns; under contention it serialized
        # every worker handoff behind a 250 ms timer — the 1->8-client
        # scaling collapse).
        # "contended" means OTHER clients' demand is queued: a client's
        # own phase-2 fan-out (several lease requests for one burst)
        # must not defeat its own idle-lease grace
        for lease, worker in grants:
            contended = any(other.conn is not lease.conn
                            for other in remaining)
            lease.future.set_result({
                "granted": True,
                "worker_address": worker.task_address,
                "worker_id": worker.worker_id.binary(),
                "contended": contended,
            })
        # Spawn exactly enough workers to cover unmet (schedulable) demand —
        # one per waiting lease, minus those already starting (parity:
        # WorkerPool::PrestartWorkers demand accounting).  TPU demand is
        # sliced against the TPU-capable starting count ONLY: plain spares
        # (refill below) can never serve a needs_tpu lease, so counting
        # them would strand TPU leases for a full boot cycle.
        plain_wait = [x for x in want_workers if not x[1]]
        tpu_wait = [x for x in want_workers if x[1]]
        starting_plain = self._starting - self._starting_tpu
        # Leases are exclusive per client: grow the pool past the
        # per-core cap by one worker per DISTINCT waiting client (total
        # pool hard-bounded at 4x the cap), else N clients on a low-core
        # host serialize behind worker handoffs even at constant total
        # work.  Idle trimming in _reap_loop shrinks the pool back.
        cap_bonus = min(len({x[2] for x in want_workers}),
                        3 * self._max_workers)
        for job_id_bin, _, _conn in plain_wait[starting_plain:]:
            self._start_worker(job_id_bin, False, cap_bonus=cap_bonus)
        for job_id_bin, _, _conn in tpu_wait[self._starting_tpu:]:
            if not self._start_worker(job_id_bin, True,
                                      cap_bonus=cap_bonus):
                # pool cap reached while idle PLAIN spares occupy it —
                # those can never serve a needs_tpu lease (eligible()
                # rejects them), so evict one to make room or the lease
                # deadlocks behind its own refill spares
                if self._cull_idle_spare(lambda w: not w.tpu_capable):
                    self._start_worker(job_id_bin, True,
                                       cap_bonus=cap_bonus)
        # anticipatory refill: actors claim pool workers permanently, so
        # creation storms drain the idle pool — respawn spares in the
        # background up to the prestart watermark (bounded by the pool
        # cap inside _start_worker) so the NEXT claims hit warm workers
        # (~4x creation rate vs cold boot on the lease critical path).
        # Skipped while any lease is still waiting (demand-driven spawns
        # own the remaining pool capacity) and while creation tasks are
        # executing — mid-wave forks steal the CPU the wave needs; the
        # reap loop's demand-driven rebuild restocks right after.
        if not remaining and not self._creating_actors:
            refill = getattr(self, "_prestart_watermark", 0) \
                - len(self._idle) - self._starting
            for _ in range(refill):
                self._start_worker(None)
        elif not self._idle:
            # Demand is queued and nothing is idle — either the pool is
            # at its cap or the leases failed _fits because
            # RESOURCES are held by leased workers (including ones
            # merely lingering in their idle grace, which generate no
            # event on their own).  Both cases: ask the owners to hand
            # back idle leases (covers grants made BEFORE the contention
            # arose, which the per-grant contended flag can't reach).
            # Rate-limited: one nudge per grace-ish window.
            now = time.monotonic()
            if now - self._last_reclaim_push >= 0.02:
                self._last_reclaim_push = now
                nudged = set()
                for w in self.workers.values():
                    conn = w.owner_conn
                    if (w.leased and not w.is_actor and conn is not None
                            and not conn.closed and id(conn) not in nudged):
                        nudged.add(id(conn))
                        conn.push("reclaim_idle", {})
            # a holder whose worker is merely BUSY right now generates
            # no event when it later idles into its grace — re-nudge on
            # a short timer until the queued demand is served (without
            # this, a waiting client stalled for the full 250 ms grace
            # of whoever got the workers first).  Exponential backoff to
            # 0.5 s: when every worker runs minutes-long tasks there is
            # nothing to reclaim and a 30 ms rescan would just burn CPU
            # for the whole saturation window.
            if not self._reclaim_timer_armed:
                delay = self._reclaim_retry_delay

                def _retry():
                    self._reclaim_timer_armed = False
                    self._reclaim_retry_delay = min(
                        0.5, self._reclaim_retry_delay * 1.6)
                    if not self._closing and self._fair.pending_count():
                        self._maybe_schedule()
                try:
                    asyncio.get_running_loop().call_later(delay, _retry)
                    self._reclaim_timer_armed = True
                except RuntimeError:
                    pass  # no loop (sync caller); the reap loop re-kicks
        if grants or not remaining:
            # demand moved: future contention starts its backoff fresh
            self._reclaim_retry_delay = 0.03
        self._note_backlog_demand(len(remaining))

    def _note_actor_claim(self) -> None:
        self._actor_claims = self._decayed_actor_claims() + 1.0
        self._actor_claims_ts = time.monotonic()

    def _decayed_actor_claims(self) -> float:
        # half-life 60 s: long enough to keep the pool stocked through a
        # benchmark-style burst sequence, short enough that a one-off
        # storm doesn't pin memory for minutes
        dt = time.monotonic() - self._actor_claims_ts
        return self._actor_claims * 0.5 ** (dt / 60.0)

    def _note_backlog_demand(self, n: int) -> None:
        """Track the decaying PEAK of the pending-lease backlog: the
        demand signal that feeds the warm-pool target (a wave that
        queued behind spawns sizes the pool for the next one)."""
        if n > self._decayed_backlog_demand():
            self._backlog_demand = float(n)
            self._backlog_demand_ts = time.monotonic()

    def _decayed_backlog_demand(self) -> float:
        dt = time.monotonic() - self._backlog_demand_ts
        return self._backlog_demand * 0.5 ** (dt / 60.0)

    def _pool_target(self) -> int:
        """Idle-pool size to maintain: boot watermark plus DEMAND — the
        larger of the recent actor-claim volume (claimed workers leave
        the pool for good) and the recent pending-lease backlog peak
        (leases that had to wait for spawns), decayed with a 60 s
        half-life.  ``max`` not sum: an actor wave appears in both
        signals, and doubling the pool doubles idle-process overhead
        for nothing.  The NEXT wave of the same size then lands on
        warm zygote forks with the fork cost off the critical path."""
        watermark = getattr(self, "_prestart_watermark", 0)
        demand = max(self._decayed_actor_claims(),
                     self._decayed_backlog_demand())
        return watermark + min(int(demand), 3 * self._max_workers)

    def _cull_idle_spare(self, predicate) -> bool:
        """Evict one idle worker matching ``predicate`` to free pool
        capacity; returns True if a worker was released."""
        for i, w in enumerate(self._idle):
            if predicate(w):
                self._idle.pop(i)
                self.workers.pop(w.worker_id, None)
                try:
                    w.conn.push("exit", {})
                except Exception:  # already gone
                    pass
                return True
        return False

    def _dec_starting_env(self, token: Any) -> None:
        if token is None:
            return
        env_hash = self._env_spawn_hash.pop(token, None)
        if env_hash is not None and self._starting_env.get(env_hash):
            self._starting_env[env_hash] -= 1

    def _dec_starting(self, was_tpu_spawn: bool) -> None:
        self._starting -= 1
        if was_tpu_spawn and self._starting_tpu > 0:
            self._starting_tpu -= 1

    def _pop_idle(self, job_id_bin: Optional[bytes],
                  needs_tpu: bool = False,
                  env_hash: Optional[str] = None,
                  exact_env_only: bool = False
                  ) -> Optional[WorkerHandle]:
        # job-dedicated workers: a worker that has loaded job code serves
        # only that job (parity: WorkerPool per-job isolation); likewise a
        # worker that applied a runtime env serves only that env, and
        # env-tasks never land on differently-polluted workers.  Two
        # passes: exact env match first, then pristine workers.
        def eligible(w, want_env):
            if needs_tpu and not w.tpu_capable:
                return False
            if w.env_hash != want_env:
                return False
            return w.job_id_bin is None or job_id_bin is None or \
                w.job_id_bin == job_id_bin

        if env_hash is not None:
            for i, w in enumerate(self._idle):
                if eligible(w, env_hash):
                    return self._idle.pop(i)
        if exact_env_only:
            # isolated env: a pristine worker can't be converted post-hoc
            return None
        for i, w in enumerate(self._idle):
            if eligible(w, None):
                return self._idle.pop(i)
        return None

    async def handle_return_worker(self, conn, data):
        # failpoint: the lease return is lost/failed — the owner RETRIES
        # it (it's classified idempotent), so duplicates must be inert
        await _fp.afailpoint("raylet.lease_return.fail")
        worker = self.workers.get(WorkerID(data["worker_id"]))
        if worker is None:
            return False
        if not worker.leased:
            # duplicate of an already-settled return (the first attempt
            # executed but its reply was lost): appending to the idle
            # pool again would grant one worker to two leases
            return False
        token = data.get("token")
        if token is not None and worker.lease_token is not None \
                and token != worker.lease_token:
            # stale duplicate from a PREVIOUS lease of this worker —
            # releasing it would free the current owner's live lease
            return False
        if data.get("job_id") is not None and worker.job_id_bin is None:
            worker.job_id_bin = data["job_id"]
        self._release_lease_resources(worker)
        if not data.get("disconnect", False):
            worker.idle_since = time.monotonic()
            self._idle.append(worker)
        self._maybe_schedule()
        return True

    def _assign_tpu_ids(self, worker: WorkerHandle, tpus: float) -> None:
        """Pick the least-loaded chips for this lease and tell the worker
        (parity: the reference raylet's GPU-id resource assignment that
        ray.get_gpu_ids reads).  Fractional demands share a chip."""
        if tpus <= 0 or not self._tpu_load:
            return
        k = max(1, int(tpus))
        ids = sorted(self._tpu_load, key=self._tpu_load.get)[:k]
        share = tpus / k
        for i in ids:
            self._tpu_load[i] += share
        worker.lease_tpu_ids = ids
        worker.lease_tpu_share = share
        try:
            worker.conn.push("lease_tpu_ids", {"ids": ids})
        except Exception:
            pass

    def _release_lease_resources(self, worker: WorkerHandle) -> None:
        if worker.leased:
            self._give(worker.lease_resources, worker.lease_bundle)
            # settle the fair queue's in-flight quota charge.  The
            # failpoint models a dropped accounting update (chaos): the
            # ledger drifts until the health beat's reconcile re-anchors
            # it on the live lease table — a drop throttles a job for at
            # most one beat, never forever.
            if worker.lease_job_key is not None and \
                    not _fp.failpoint("raylet.quota.account_drop"):
                self._fair.release(worker.lease_job_key,
                                   worker.lease_resources)
            worker.lease_job_key = None
            worker.leased = False
            worker.lease_token = None
            worker.owner_conn = None
            worker.lease_resources = {}
            worker.lease_bundle = None
            if worker.lease_tpu_ids:
                for i in worker.lease_tpu_ids:
                    if i in self._tpu_load:
                        self._tpu_load[i] = max(
                            0.0, self._tpu_load[i] - worker.lease_tpu_share)
                worker.lease_tpu_ids = []
                worker.lease_tpu_share = 0.0
                try:
                    worker.conn.push("lease_tpu_ids", {"ids": []})
                except Exception:
                    pass

    async def handle_lease_worker_for_actor(self, conn, data):
        """GCS asks this node to host an actor: lease a worker, push the
        creation task to it, reply with its task-server address."""
        return await self._lease_and_create_actor(conn, data)

    async def handle_lease_workers_for_actors(self, conn, data):
        """Batched actor bring-up (GCS pipelined fan-out): EVERY lease
        in the batch enqueues before the first grant resolves — one
        scheduling pass sees the whole wave, so worker spawns cover the
        full deficit at once instead of trickling in per actor — then
        the creation tasks push to their granted workers concurrently.
        Per-actor results; one actor's failure (no grant, constructor
        raised, worker died) never blocks its batch-mates."""
        entries = data["actors"]

        async def one(entry):
            try:
                res = await self._lease_and_create_actor(conn, entry)
            except Exception as e:  # noqa: BLE001 — isolate per actor
                res = {"granted": False,
                       "reason": f"{type(e).__name__}: {e}"}
            res["actor_id"] = entry["actor_id"]
            return res

        # Enqueue-all-then-schedule-once: every per-actor coroutine runs
        # to its grant await (appending its PendingLease) while the
        # scheduler is suspended, then ONE pass grants the whole wave —
        # per-enqueue passes re-scanned a growing queue (O(n^2) lease
        # evaluations, each an O(idle-pool) eligibility scan).
        self._sched_suspended = True
        try:
            tasks = [asyncio.ensure_future(one(e)) for e in entries]
            # one loop yield runs every task to its first real await
            # (the lease future) — all enqueues land before the pass
            await asyncio.sleep(0)
        finally:
            self._sched_suspended = False
        self._maybe_schedule()
        results = await asyncio.gather(*tasks)
        return {"results": list(results)}

    async def _lease_and_create_actor(self, conn, data):
        resources = dict(data.get("resources", {}))
        # the lease path resolves (and refuses missing) bundles itself, so
        # an unbound fallback to the node pool is impossible by design
        reply = await self.handle_request_worker_lease(conn, {
            "resources": resources,
            "job_id": data.get("job_id"),
            "placement_group_id": data.get("placement_group_id"),
            "bundle_index": data.get("bundle_index", -1),
            "strategy": "DEFAULT",
            "env_hash": data.get("env_hash"),
            "env_spawn": data.get("env_spawn"),
        })
        if not reply.get("granted"):
            return {"granted": False, "reason": str(reply)}
        worker = self.workers.get(WorkerID(reply["worker_id"]))
        if worker is None:
            return {"granted": False, "reason": "worker vanished"}
        worker.is_actor = True
        self._note_actor_claim()
        payload = {"spec_blob": data["spec_blob"]}
        # Attach node-cached function + syspath blobs: 25 actors of one
        # class on one node then cost ONE GCS fetch instead of 25 (the
        # per-worker fetches were the dominant GCS load in creation
        # storms — parity motivation: gcs_actor_scheduler.cc batches the
        # equivalent metadata on the lease path).
        try:
            extra = await self._actor_creation_blobs(data["spec_blob"])
            payload.update(extra)
        except Exception:  # cache is best-effort; workers can self-fetch
            logger.debug("actor blob prefetch failed", exc_info=True)
        self._creating_actors += 1
        try:
            result = await worker.conn.call(
                "create_actor", payload, timeout=120.0)
        except (rpc.ConnectionLost, rpc.RpcError) as e:
            self._on_worker_dead(worker, f"actor creation failed: {e}")
            return {"granted": False, "reason": str(e)}
        finally:
            self._creating_actors -= 1
        if not result.get("ok"):
            # creation raised in user code: actor is dead on arrival
            self._release_lease_resources(worker)
            worker.idle_since = time.monotonic()
            self._idle.append(worker)
            worker.is_actor = False
            return {"granted": False, "reason": result.get("error", "unknown"),
                    "creation_error": True}
        return {"granted": True, "worker_task_address": worker.task_address,
                "worker_id": worker.worker_id.binary()}

    async def _actor_creation_blobs(self, spec_blob: bytes) -> Dict[str, Any]:
        """Node-level cache of (function blob, job syspath blob) for actor
        creation, keyed off the pickled spec's ids.  LRU-bounded, and a
        miss (None reply) is NOT cached — a transient GCS anomaly must not
        permanently disable the prefetch for that key."""
        import pickle as pickle_mod
        spec = pickle_mod.loads(spec_blob)
        cache = getattr(self, "_creation_blob_cache", None)
        if cache is None:
            from collections import OrderedDict
            cache = self._creation_blob_cache = OrderedDict()

        async def lookup(key, fetch):
            blob = cache.get(key)
            if blob is not None:
                cache.move_to_end(key)
                return blob
            blob = await fetch()
            if blob is not None:
                cache[key] = blob
                while len(cache) > 128:
                    cache.popitem(last=False)
            return blob

        out: Dict[str, Any] = {}
        fn_blob = await lookup(
            ("fn", spec.function_id),
            lambda: self.gcs_conn.call(
                "get_function", {"function_id": spec.function_id}))
        if fn_blob is not None:
            out["function_blob"] = fn_blob
        if spec.job_id is not None:
            sp_blob = await lookup(
                ("syspath", spec.job_id.binary()),
                lambda: self.gcs_conn.call("kv_get", {
                    "key": f"syspath:{spec.job_id.hex()}",
                    "namespace": "_internal"}))
            if sp_blob is not None:
                out["syspath_blob"] = sp_blob
                out["syspath_job"] = spec.job_id.binary()
        return out

    # ------------------------------------------------------------------
    # telemetry flush (the per-raylet producer half of the metrics
    # pipeline; parity: the per-node MetricsAgent push loop,
    # metrics_agent.py:374)
    # ------------------------------------------------------------------
    def _sample_gauges(self) -> None:
        """Point-in-time gauges refreshed right before each flush; all
        tagged with this node so per-node series don't overwrite each
        other in the GCS aggregation."""
        tags = {"node": self.node_id.hex()[:12]}
        _tm.set_gauge("ray_tpu_sched_pending_leases",
                      "worker-lease requests queued on the raylet",
                      self._fair.pending_count(), tags)
        for job, n in self._fair.throttled_total.items():
            _tm.set_gauge("ray_tpu_sched_quota_throttled_total",
                          "lease grants skipped or rejected by the "
                          "job's quota ceiling (cumulative)",
                          n, {**tags, "job": job})
        _tm.set_gauge("ray_tpu_transfer_inflight_pulls",
                      "object transfers currently being received",
                      len(self._inflight_pulls), tags)
        _tm.set_gauge("ray_tpu_workers_total",
                      "worker processes registered on the node",
                      len(self.workers), tags)
        _tm.set_gauge("ray_tpu_workers_idle",
                      "idle pool workers on the node",
                      len(self._idle), tags)
        try:
            stats = self.store.stats_ex()
        except Exception:  # noqa: BLE001 — stats must not kill the loop
            stats = self.store.stats()
        _tm.set_gauge("ray_tpu_arena_used_bytes",
                      "object-store arena bytes allocated",
                      stats.get("used", 0), tags)
        _tm.set_gauge("ray_tpu_arena_num_objects",
                      "objects resident in the arena",
                      stats.get("num_objects", 0), tags)
        cap = stats.get("capacity", 0)
        _tm.set_gauge("ray_tpu_arena_capacity_bytes",
                      "object-store arena capacity", cap, tags)
        if cap:
            # the arena-pressure signal the history plane's recording
            # rule (cluster:arena_occupancy) and the ArenaPressure
            # alert subscribe to
            _tm.set_gauge("ray_tpu_arena_occupancy_fraction",
                          "arena bytes used / capacity",
                          stats.get("used", 0) / cap, tags)
        self._sample_job_arena_bytes(tags)
        if "reuse_hits" in stats:
            hits = stats["reuse_hits"]
            misses = stats.get("reuse_misses", 0)
            rate = hits / (hits + misses) if hits + misses else 0.0
            _tm.set_gauge("ray_tpu_arena_reuse_hit_rate",
                          "fraction of allocations served from the "
                          "client's warm slab bucket", rate, tags)
            _tm.set_gauge("ray_tpu_arena_doomed_objects",
                          "deleted-while-pinned objects awaiting their "
                          "last release", stats.get("doomed_current", 0),
                          tags)
            _tm.set_gauge("ray_tpu_arena_active_buckets",
                          "slab buckets with live allocations",
                          stats.get("active_buckets", 0), tags)
            _tm.set_gauge("ray_tpu_arena_bucket_free_bytes",
                          "free bytes parked in per-client slab buckets",
                          stats.get("bucket_free_bytes", 0), tags)
        if "shard_contention" in stats:
            _tm.set_gauge("ray_tpu_store_shard_contention_total",
                          "cumulative contended metadata-shard lock "
                          "acquisitions (striping health: near-zero "
                          "means writers aren't colliding)",
                          stats.get("shard_contention", 0), tags)
        _tm.set_gauge("ray_tpu_store_spill_objects",
                      "objects resident in the spill tier",
                      len(self._spilled), tags)

    #: primaries sampled per flush for the per-job arena rollup (the
    #: gauge is approximate on nodes holding more; the cap bounds the
    #: lease/release work a flush tick can do)
    _JOB_ARENA_SAMPLE_CAP = 4096

    def _sample_job_arena_bytes(self, tags) -> None:
        """Per-job arena occupancy: sum primary-copy sizes by the job
        embedded in each ObjectID.  Jobs reported last tick but gone
        now are zeroed so their gauges age out instead of flushing a
        stale value forever."""
        per_job: Dict[str, int] = {}
        primaries = list(self._primary)
        truncated = len(primaries) > self._JOB_ARENA_SAMPLE_CAP
        for oid in primaries[:self._JOB_ARENA_SAMPLE_CAP]:
            lease = self.store.lease(oid)
            if lease is None:
                continue
            _, size = lease
            self.store.release(oid)
            job = oid.job_id().hex()
            per_job[job] = per_job.get(job, 0) + size
        if truncated:
            # a truncated sweep can MISS a job that still holds bytes:
            # zeroing it would flap the gauge between truth and 0 as
            # set order churns — keep last values (approximate but
            # monotone-consistent) until the node drains below the cap
            self._job_arena_reported |= {j for j, n in per_job.items()
                                         if n}
        else:
            for job in self._job_arena_reported - set(per_job):
                per_job[job] = 0  # drained: age the gauge out via 0
            self._job_arena_reported = {j for j, n in per_job.items()
                                        if n}
        for job, nbytes in per_job.items():
            _tm.set_gauge("ray_tpu_job_arena_bytes",
                          "arena bytes held by primary copies, by "
                          "owning job", nbytes, dict(tags, job=job))

    async def _metrics_flush_loop(self) -> None:
        """Batch registry deltas + spans to the GCS metrics/span tables
        every ``metrics_report_period_s``.  Drop-don't-block: an
        unreachable GCS costs this window's deltas, never the loop."""
        from ray_tpu.util import metrics as metrics_mod

        period = max(0.25, getattr(self.config,
                                   "metrics_report_period_s", 5.0))
        synced_conn = None  # re-probe on failure AND after a reconnect
        source = f"raylet-{self.node_id.hex()[:12]}"
        while not self._closing:
            # active profiling flushes at >= 1 Hz (short windows must
            # not wait out the 5 s metrics period)
            await asyncio.sleep(min(period, 1.0) if _prof.pending()
                                else period)
            # profile records flush even with metrics disabled: the
            # profiler is armed explicitly, and skipping drain here
            # would also leave pending() true -> 1 Hz ticks forever
            # (trace spans likewise flush independently of metrics)
            if not _tm.enabled() and not _prof.pending() \
                    and not _trace.pending():
                continue
            conn = self.gcs_conn
            if conn is None or conn.closed:
                continue
            if conn is not synced_conn:
                # a restarted GCS may run on a different host clock
                if await _tm.measure_clock_offset(conn) is not None:
                    synced_conn = conn
            try:
                records: list = []
                spans: list = []
                if _tm.enabled():
                    self._sample_gauges()
                    fstats = _flight.stats()
                    if fstats is not None:
                        _tm.flight_frames(fstats["frames_recorded"])
                    _tm.presample()
                    records = metrics_mod.flush_all()
                    spans = _tm.drain_spans(source)
                profile = _prof.drain()
                if records:
                    self._metrics_report_seq += 1
                    await conn.call("report_metrics",
                                    {"records": records, "source": source,
                                     "seq": self._metrics_report_seq},
                                    timeout=2.0)
                if spans:
                    await conn.call("report_spans", {"spans": spans},
                                    timeout=2.0)
                tspans = _trace.drain(source)
                if tspans:
                    await conn.call("report_trace_spans",
                                    {"spans": tspans}, timeout=2.0)
                if profile:
                    node = self.node_id.hex()
                    for rec in profile:
                        rec["node"] = node
                        rec["source"] = source
                    await conn.call("report_profile",
                                    {"records": profile}, timeout=2.0)
            except (rpc.ConnectionLost, rpc.RpcError,
                    asyncio.TimeoutError, OSError):
                pass  # dropped: counters re-accumulate, gauges refresh
            except Exception:
                logger.exception("metrics flush iteration failed")

    # ------------------------------------------------------------------
    # state API (per-node sources; parity: raylet handlers behind
    # StateDataSourceClient state_manager.py:130)
    # ------------------------------------------------------------------
    async def handle_debug_state(self, conn, data):
        """Event-loop lag + per-handler timings (event_stats parity),
        plus the raylet's live control/data-plane depths for the status
        surface."""
        mon = getattr(self, "_loop_monitor", None)
        out = mon.snapshot() if mon is not None else {}
        out["pending_leases"] = self._fair.pending_count()
        out["draining"] = self._draining
        out["fair_queue"] = self._fair.snapshot()
        out["inflight_pulls"] = len(self._inflight_pulls)
        out["workers"] = len(self.workers)
        out["idle_workers"] = len(self._idle)
        out["starting_workers"] = self._starting
        out["warm_pool_target"] = self._pool_target()
        out["creating_actors"] = self._creating_actors
        out["spilled_objects"] = len(self._spilled)
        out["spill_bytes"] = self._spill_bytes
        try:
            out["store"] = self.store.stats_ex()
            out["store"]["bucket_occupancy"] = \
                self.store.bucket_occupancy()
        except Exception:  # noqa: BLE001
            out["store"] = self.store.stats()
        return out

    async def handle_stack_traces(self, conn, data):
        """All-thread stack dumps from every worker on this node PLUS
        the raylet process itself (parity: the dashboard reporter's
        py-spy fan-out; the raylet's own loop is where transfer/lease
        wedges live, so `ray-tpu stack` must see it too)."""
        async def one(worker):
            try:
                return await asyncio.wait_for(
                    worker.conn.call("stack_trace", {}), 10.0)
            except Exception as e:  # noqa: BLE001 — wedged workers are
                return {"pid": worker.pid,  # exactly what you're hunting
                        "error": f"{type(e).__name__}: {e}"}

        import threading
        import traceback
        names = {t.ident: t.name for t in threading.enumerate()}
        own = [{"thread": names.get(ident, str(ident)),
                "stack": "".join(traceback.format_stack(frame))}
               for ident, frame in sys._current_frames().items()]
        dumps = await asyncio.gather(
            *(one(w) for w in list(self.workers.values())))
        return {"node_id": self.node_id.hex(), "workers": dumps,
                "raylet": {"pid": os.getpid(), "threads": own}}

    async def handle_list_workers(self, conn, data):
        return [{"worker_id": w.worker_id.hex(), "pid": w.pid,
                 "leased": w.leased, "is_actor": w.is_actor,
                 "lease_resources": w.lease_resources}
                for w in self.workers.values()]

    async def handle_list_objects(self, conn, data):
        limit = int(data.get("limit", 1000))
        out = []
        for oid in list(self._primary)[:limit]:
            lease = self.store.lease(oid)
            if lease is None:
                continue
            _, size = lease
            self.store.release(oid)
            out.append({"object_id": oid.hex(), "size": size,
                        "node_id": self.node_id.hex()})
        stats = await self.handle_store_stats(conn, {})
        return {"objects": out, "store_stats": stats,
                "num_spilled": stats["num_spilled"]}

    # ------------------------------------------------------------------
    # placement-group bundles (PlacementGroupResourceManager)
    # ------------------------------------------------------------------
    async def handle_prepare_bundle(self, conn, data):
        # bundle waves are control-plane bursts too: pause the
        # background pool rebuild while one is in flight
        self._last_lease_ts = time.monotonic()
        resources = dict(data["resources"])
        key = (data["pg_id"], data["bundle_index"])
        if key in self._bundle_totals:
            return True  # idempotent: GCS retry of an already-held bundle
        if not all(self.resources_available.get(k, 0.0) >= v
                   for k, v in resources.items()):
            return False
        for k, v in resources.items():
            self.resources_available[k] = self.resources_available.get(k, 0.0) - v
        self._bundles[key] = dict(resources)  # held but uncommitted
        self._bundle_totals[key] = dict(resources)
        return True

    async def handle_commit_bundle(self, conn, data):
        key = (data["pg_id"], data["bundle_index"])
        return key in self._bundles

    async def handle_return_bundle(self, conn, data):
        key = (data["pg_id"], data["bundle_index"])
        self._bundle_totals.pop(key, None)
        remaining = self._bundles.pop(key, None)
        if remaining is not None:
            # refund only the unleased remainder; shares held by live
            # leases come back through _give when each worker releases
            for k, v in remaining.items():
                self.resources_available[k] = \
                    self.resources_available.get(k, 0.0) + v
        # gang semantics: leases from a returned bundle are revoked — kill
        # their workers so the rescheduled gang can't double-book the chips
        for worker in list(self.workers.values()):
            if worker.leased and worker.lease_bundle == key:
                if worker.proc is not None:
                    worker.proc.terminate()
                self._on_worker_dead(worker, "placement group bundle returned")
        # queued leases against the bundle can never be granted now — fail
        # them instead of leaving their futures pending forever
        for lease in self._fair.pending():
            if lease.bundle == key:
                self._fair.remove(lease)
                if not lease.future.done():
                    lease.future.set_result(
                        {"error": "placement group bundle removed"})
        self._maybe_schedule()
        return True

    # ------------------------------------------------------------------
    # object plane: local store service
    # ------------------------------------------------------------------
    async def handle_object_create(self, conn, data):
        """Allocate store space, spilling/evicting to make room.

        Retry loop parity: plasma's CreateRequestQueue — under a burst
        of concurrent creates the primaries that COULD be spilled may
        not be sealed yet (create happens before seal), so a single
        spill-then-alloc pass fails spuriously; retrying lets in-flight
        writers seal and become spillable."""
        object_id = ObjectID(data["object_id"])
        size = data["size"]
        if size > self.store_capacity:
            raise ObjectStoreFullError(
                f"object of {size} bytes exceeds the store capacity "
                f"({self.store_capacity}) — no amount of spilling fits it")
        # per-client allocation affinity: creates from one connection
        # (i.e. one producing process) reuse blocks that process freed,
        # so its writes land on page-table-warm offsets.  Fault-expensive
        # hosts write cold pages ~10x slower — with a single shared free
        # list, four concurrent putters permanently shuffled each other
        # onto cold blocks (the multi-client put collapse).
        hint = conn.context.get("alloc_hint")
        if hint is None:
            hint = conn.context["alloc_hint"] = \
                (id(conn) >> 4) % 63 + 1  # 0 is the raylet's own bucket
        deadline = time.monotonic() + 30.0
        while True:
            await self._maybe_spill(size)
            try:
                offset, _ = self.store.alloc(object_id, size, hint)
                return {"offset": offset, "size": size}
            except ValueError:
                raise  # already exists — caller bug, don't retry
            except ObjectStoreFullError:
                if time.monotonic() > deadline:
                    raise
                # fragmentation relief, gated on its signature: the
                # alloc failed although accounting says the object FITS
                # below the pressure threshold — long-lived primaries
                # can checkerboard the striped arena (one block pinning
                # each stripe's region start) until no free run fits
                # ``size`` even with half the arena free.  Spilling is
                # the only block *mover*, so force a small sweep — the
                # spilled primary's region opens and the retry lands.
                # Above the threshold this is genuine pressure: the
                # _maybe_spill at the top of the loop already sweeps,
                # and in-flight writers sealing is the usual cure.
                frac = getattr(self.config, "object_spill_threshold",
                               -1.0)
                if frac is None or frac < 0:
                    frac = self.config.object_spilling_threshold
                if self.store.used() + size <= frac * self.store_capacity:
                    await self._spill_for_fragmentation(size)
                await asyncio.sleep(0.05)

    async def handle_object_seal(self, conn, data):
        object_id = ObjectID(data["object_id"])
        self.store.seal(object_id)
        self._mark_primary(object_id, tuple(data["owner_address"])
                           if data.get("owner_address") else None)
        return True

    def _mark_primary(self, object_id: ObjectID, owner: Optional[tuple]) -> None:
        if object_id not in self._primary:
            if self.store.lease(object_id) is not None:  # pin primary copy
                self._primary.add(object_id)
        if owner is not None:
            self._owner_of[object_id] = owner

    async def handle_object_get(self, conn, data):
        """Resolve objects to {offset,size} leases, pulling remote /
        spilled copies as needed.  The client must release_objects."""
        ids = [ObjectID(b) for b in data["object_ids"]]
        owners = data.get("owners", {})
        timeout = data.get("timeout")
        deadline = None if timeout is None else time.monotonic() + timeout
        out = {}
        for oid in ids:
            lease = self.store.lease(oid)
            if lease is None:
                ok = await self._make_local(oid, owners.get(oid.binary()),
                                            deadline)
                lease = self.store.lease(oid) if ok else None
            if lease is None:
                out[oid.binary()] = None
            else:
                out[oid.binary()] = {"offset": lease[0], "size": lease[1]}
        return out

    async def _make_local(self, oid: ObjectID, owner: Optional[tuple],
                          deadline: Optional[float]) -> bool:
        """Restore from spill or pull from remote holders (serialized
        per object; concurrent readers share one transfer)."""
        entry = self._pull_locks.get(oid)
        if entry is None:
            entry = self._pull_locks[oid] = [asyncio.Lock(), 0]
        entry[1] += 1
        try:
            async with entry[0]:
                return await self._make_local_locked(oid, owner, deadline)
        finally:
            entry[1] -= 1
            if entry[1] == 0 and self._pull_locks.get(oid) is entry:
                del self._pull_locks[oid]

    async def _make_local_locked(self, oid: ObjectID,
                                 owner: Optional[tuple],
                                 deadline: Optional[float]) -> bool:
        if self.store.contains(oid):
            return True
        if oid in self._spilled:
            if await self._restore_from_spill(oid):
                return True
            # unreadable/failed local restore: fall through to the
            # owner's directory — other copies or a URI blob may exist
        if owner is None:
            owner = self._owner_of.get(oid)
        if owner is None:
            return False
        # ownership-based directory: ask the owner where copies live
        failures = 0
        while True:
            try:
                owner_conn = await self.pool.get((owner[1], owner[2]))
                locs = await owner_conn.call(
                    "get_object_locations",
                    {"object_id": oid.binary()}, timeout=10.0)
            except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError,
                    OSError):
                return False
            if locs is None:
                return False  # owner no longer knows the object
            my_addr = self.server.address
            sealed = [tuple(a) for a in locs.get("nodes", [])
                      if tuple(a) != my_addr]
            partials = [tuple(a) for a in (locs.get("partial_nodes") or [])
                        if tuple(a) != my_addr]
            if (sealed or partials) and await self._pull_object(
                    oid, sealed, partials, owner_conn):
                return True
            if locs.get("spilled_uri"):
                # external tier: restore directly, no matter which
                # node spilled it (it may be dead — that's the point)
                if await self._restore_from_uri(oid, locs["spilled_uri"]):
                    return True
            if locs.get("spilled_on"):
                node_addr = tuple(locs["spilled_on"])
                if node_addr == my_addr:
                    return await self._restore_from_spill(oid)
                if await self._pull_object(oid, [node_addr], [],
                                           owner_conn):
                    return True
            if locs.get("pending"):
                # object not produced yet; wait and retry
                if deadline is not None and time.monotonic() > deadline:
                    return False
                await asyncio.sleep(0.05)
                continue
            failures += 1
            if not (sealed or partials) or failures >= 3:
                return False
            # every source failed mid-transfer: re-query the owner —
            # fresh holders may have sealed since (chained broadcast)
            if deadline is not None and time.monotonic() > deadline:
                return False
            await asyncio.sleep(0.1)

    async def _pull_object(self, oid: ObjectID,
                           sealed_nodes: List[rpc.Address],
                           partial_nodes: List[rpc.Address],
                           owner_conn: Optional[rpc.Connection]) -> bool:
        """Windowed, multi-source pull (parity: ObjectManager Push/Pull,
        pull_manager.h).

        Up to ``object_transfer_window`` chunk requests are kept in
        flight per source, and sources serve disjoint chunks off one
        shared queue, so holders stripe the object between them and a
        faster source automatically carries more.  A source that dies
        mid-transfer re-queues its outstanding chunks for the survivors
        — the transfer restarts only when EVERY source is gone.  While
        the transfer runs it is registered as a *partial* location with
        the owner; once sealed it is registered as a full location, so
        later pullers fan out across the copies instead of all draining
        the producer.
        """
        config = self.config
        window = max(1, getattr(config, "object_transfer_window", 8))
        max_sources = max(1, getattr(config, "object_transfer_max_sources",
                                     4))
        chunk = config.object_transfer_chunk_size
        chunk_timeout = getattr(config, "object_transfer_chunk_timeout_s",
                                30.0)
        partial_cfg = getattr(config, "object_transfer_partial_locations",
                              True)

        t_start = time.monotonic()
        t_wall = time.time()  # span timestamps are wall-clock
        # sample rather than slice when many holders exist: a prefix of
        # dead nodes (the owner never unlearns crashed holders) would
        # otherwise shadow live copies further down the list on every
        # attempt.  Seeded stream for reproducible test runs.
        sealed_pick = list(sealed_nodes)
        if len(sealed_pick) > max_sources + 2:
            sealed_pick = _probe_rng.sample(sealed_pick, max_sources + 2)
        candidates = sealed_pick
        candidates += [addr for addr in partial_nodes[:2]
                       if addr not in candidates]

        async def probe(addr: rpc.Address):
            try:
                conn = await self.pool.get(addr)
                meta = await conn.call(
                    "object_pull_start", {"object_id": oid.binary()},
                    timeout=10.0)
            except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError,
                    OSError):
                return None
            if meta is None:
                return None
            return {"addr": addr, "conn": conn, "size": meta["size"],
                    "partial": bool(meta.get("partial")), "dead": False,
                    "meta": meta}

        if not candidates:
            return False
        # two-phase probe wait: a single black-holed candidate (e.g. a
        # stale partial location from a crashed puller) must not stall
        # transfer start for its full timeout when a healthy source
        # answered in milliseconds.  Stragglers keep running in the
        # background and release their pins when they land.
        probe_tasks = [asyncio.ensure_future(probe(a)) for a in candidates]
        done, pending_probes = await asyncio.wait(probe_tasks, timeout=2.0)
        if not any(t.result() is not None for t in done):
            if pending_probes:
                more, pending_probes = await asyncio.wait(pending_probes,
                                                          timeout=10.0)
                done |= more
        for t in pending_probes:
            t.add_done_callback(self._release_late_probe(oid))
        probed = [t.result() for t in done if t.result() is not None]
        if not probed:
            return False
        # prefer sealed copies over partial chains (bounded waits beat
        # no waits only when there's nothing better), then cap the
        # stripe width
        probed.sort(key=lambda s: s["partial"])
        sources = [s for s in probed if s["size"] == probed[0]["size"]]
        sources, spares = sources[:max_sources], sources[max_sources:]
        await self._release_sources(oid, spares)
        if not sources:
            return False
        size = sources[0]["size"]

        registered_partial = False
        if partial_cfg and owner_conn is not None and size > chunk:
            # announce the in-progress copy so concurrent pullers can
            # chain on this node instead of re-draining the holders
            try:
                await owner_conn.call("object_location_added", {
                    "object_id": oid.binary(),
                    "node": list(self.server.address),
                    "partial": True}, timeout=5.0)
                registered_partial = True
            except (rpc.ConnectionLost, rpc.RpcError,
                    asyncio.TimeoutError):
                pass

        try:
            await self._maybe_spill(size)
            offset, view = self.store.alloc(oid, size)
        except ValueError:
            # concurrently produced on this node (e.g. a local worker
            # sealed it while we probed)
            await self._release_sources(oid, sources)
            return self.store.contains(oid)
        except ObjectStoreFullError:
            await self._release_sources(oid, sources)
            if registered_partial:
                await self._retract_partial(oid, owner_conn)
            raise

        inflight = _InflightPull(size, offset, chunk)
        self._inflight_pulls[oid] = inflight
        pending = deque((off, min(chunk, size - off))
                        for off in range(0, size, chunk))
        total_chunks = len(pending)
        state = {"active": 0}
        # sinks write into the arena only while the transfer owns the
        # block: a straggler reply arriving after cleanup (its request
        # timed out and the chunk was re-fetched elsewhere) must not
        # scribble over a freed/re-allocated region
        alive = {"ok": True}
        loop = asyncio.get_running_loop()

        async def write_chunk(off: int, data) -> None:
            if len(data) >= (1 << 18):
                # GIL-releasing memmove off the event loop: cold arena
                # pages fault at ~0.3 GB/s on sandboxed kernels, which
                # would stall every other RPC this raylet serves
                await loop.run_in_executor(
                    None, self.store.write_range, offset + off, data)
            else:
                view[off:off + len(data)] = data

        async def fetch_loop(src) -> None:
            while not inflight.failed:
                if len(inflight.have) >= total_chunks or src["dead"]:
                    return
                try:
                    item = pending.popleft()
                except IndexError:
                    if state["active"] == 0:
                        return  # done, or every remaining chunk is lost
                    await asyncio.sleep(0.02)
                    continue
                off, n = item
                if off // chunk in inflight.have:
                    continue  # already landed via the shm fast path
                state["active"] += 1
                _tm.transfer_window_occupancy(state["active"])
                got = [0]

                def sink(payload, _off=off, _got=got):
                    # runs synchronously at frame arrival: the chunk
                    # goes from the socket buffer straight into the
                    # arena — no intermediate bytes object
                    if alive["ok"] and not inflight.failed:
                        view[_off:_off + len(payload)] = payload
                        _got[0] = len(payload)

                try:
                    reply = await src["conn"].call(
                        "object_pull_chunk",
                        {"object_id": oid.binary(), "offset": off,
                         "n": n}, timeout=chunk_timeout, sink=sink)
                    if got[0] != n:
                        # no OOB payload: a partial holder / fallback
                        # path served plain bytes (or dropped the object)
                        if reply is None or len(reply) != n:
                            raise IOError(
                                "holder dropped object mid-transfer")
                        await write_chunk(off, reply)
                except (rpc.ConnectionLost, rpc.RpcError,
                        asyncio.TimeoutError, OSError):
                    # mid-transfer failover: the chunk goes back on the
                    # shared queue for the surviving sources; this
                    # source serves no further chunks
                    pending.append(item)
                    if not src["dead"]:
                        _tm.transfer_failover()
                    src["dead"] = True
                    return
                finally:
                    state["active"] -= 1
                _tm.transfer_chunk("net", n)
                inflight.mark(off // chunk)

        async def pump(src) -> None:
            n = min(window, total_chunks)
            # return_exceptions: one crashing fetcher must not strand
            # its siblings mid-write while cleanup deletes the object
            for res in await asyncio.gather(
                    *(fetch_loop(src) for _ in range(n)),
                    return_exceptions=True):
                if isinstance(res, BaseException):
                    logger.exception("pull fetcher failed for %s",
                                     oid.hex()[:12], exc_info=res)
                    inflight.fail()

        # same-host fast path: the holder's arena file is visible on
        # this machine (virtual clusters / multi-raylet hosts) — copy
        # arena-to-arena instead of paying the socket stack.  The
        # source pin taken at pull_start guards the range either way.
        shm_src = None
        if getattr(config, "object_transfer_shm_fastpath", True):
            for s in sources:
                meta = s.get("meta") or {}
                path = meta.get("store_path")
                if not s["partial"] and path and path != self.store.path \
                        and "offset" in meta and os.path.exists(path):
                    shm_src = s
                    break
        try:
            if shm_src is not None:
                try:
                    await self._pull_via_shm(shm_src, size, offset,
                                             inflight, chunk)
                except Exception:  # noqa: BLE001 — any shm failure
                    logger.exception(  # falls back to the socket path
                        "shm fast-path pull of %s failed; falling back "
                        "to network transfer", oid.hex()[:12])
            if len(inflight.have) < total_chunks:
                await asyncio.gather(*(pump(src) for src in sources))
        finally:
            alive["ok"] = False
            ok = len(inflight.have) >= total_chunks and not inflight.failed
            # seal BEFORE popping the inflight entry (no await between):
            # a chained puller must always find the copy either inflight
            # or sealed — the source releases below can take seconds and
            # previously left a neither-state window that broke chains
            if ok:
                self.store.seal(oid)
            self._inflight_pulls.pop(oid, None)
            if not ok:
                inflight.fail()
                self.store.delete(oid)
            await self._release_sources(oid, sources)
        path = "shm" if shm_src is not None else "net"
        elapsed = time.monotonic() - t_start
        _tm.transfer_pull_done(ok, path, size, elapsed, len(sources))
        _tm.record_span(
            "transfer", f"pull:{oid.hex()[:12]}", t_wall,
            t_wall + elapsed, bytes=size, sources=len(sources),
            path=path, ok=ok, node=self.node_id.hex()[:12])
        if not ok:
            if registered_partial:
                await self._retract_partial(oid, owner_conn)
            return False
        log = logger.info if size >= (64 << 20) else logger.debug
        log("pulled %s (%d MiB) in %.2fs via %s from %d source(s)",
            oid.hex()[:12], size >> 20, elapsed,
            "shm" if shm_src is not None else "net", len(sources))
        # secondary copy: not pinned, evictable.  Register it with the
        # owner so later pullers stripe across it and the owner's free
        # fan-out reaches this node.
        if owner_conn is not None:
            try:
                await owner_conn.call("object_location_added", {
                    "object_id": oid.binary(),
                    "node": list(self.server.address),
                    "partial": False}, timeout=5.0)
            except (rpc.ConnectionLost, rpc.RpcError,
                    asyncio.TimeoutError):
                pass
        return True

    def _release_late_probe(self, oid: ObjectID):
        """Done-callback for a probe that outlived the two-phase wait:
        if it did reach its holder, hand the pin straight back."""
        def _cb(task):
            src = None if task.cancelled() else task.result()
            if src is None or self._closing:
                return
            asyncio.ensure_future(self._release_sources(oid, [src]))
        return _cb

    def _peer_arena(self, path: str, capacity: int) -> list:
        """Cached mapping of a same-host peer raylet's arena as a
        ``[mmap, base_addr, export, refcount]`` entry.  Each call also
        sweeps mappings whose backing file is gone (a dead peer's
        unlinked arena would otherwise stay pinned in tmpfs until this
        raylet stops); in-use entries (refcount > 0) are spared."""
        for stale in [p for p, e in self._peer_arenas.items()
                      if e[3] == 0 and not os.path.exists(p)]:
            ent = self._peer_arenas.pop(stale)
            ent[2] = None  # drop the export before unmapping
            try:
                ent[0].close()
            except BufferError:
                pass
        ent = self._peer_arenas.get(path)
        if ent is None:
            from ray_tpu.core.object_store import map_arena

            mm, base, export = map_arena(path, capacity)
            ent = self._peer_arenas[path] = [mm, base, export, 0]
        return ent

    async def _pull_via_shm(self, src, size: int, dest_offset: int,
                            inflight: _InflightPull, chunk: int) -> None:
        """Copy the object straight out of a same-host holder's arena:
        chunked GIL-releasing memmoves in the executor, with per-chunk
        progress marks so partial-location chaining still works."""
        meta = src["meta"]
        ent = self._peer_arena(meta["store_path"], meta["capacity"])
        base = ent[1]
        src_off = meta["offset"]
        loop = asyncio.get_running_loop()
        ent[3] += 1  # hold the mapping against the stale sweep
        try:
            pos = 0
            while pos < size and not inflight.failed:
                n = min(chunk, size - pos)
                await loop.run_in_executor(
                    None, self.store.copy_in, dest_offset + pos,
                    base + src_off + pos, n)
                _tm.transfer_chunk("shm", n)
                inflight.mark(pos // chunk)
                pos += n
        finally:
            ent[3] -= 1

    async def _release_sources(self, oid: ObjectID, sources) -> None:
        """Best-effort pull_end on every source — a dead holder's pins
        are reclaimed by its disconnect cleanup instead (a raising
        ``finally`` here used to mask the transfer's real error)."""
        for src in sources:
            conn = src["conn"]
            if conn.closed:
                continue
            try:
                await conn.call("object_pull_end",
                                {"object_id": oid.binary()}, timeout=5.0)
            except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError,
                    OSError):
                pass

    async def _retract_partial(self, oid: ObjectID,
                               owner_conn: Optional[rpc.Connection]) -> None:
        if owner_conn is None or owner_conn.closed:
            return
        try:
            await owner_conn.call("object_location_removed", {
                "object_id": oid.binary(),
                "node": list(self.server.address),
                "partial": True}, timeout=5.0)
        except (rpc.ConnectionLost, rpc.RpcError, asyncio.TimeoutError):
            pass

    async def handle_object_pull_start(self, conn, data):
        # failpoint: the transfer source fails at serve start (chaos)
        await _fp.afailpoint("raylet.pull_start.serve")
        oid = ObjectID(data["object_id"])
        lease = self.store.lease(oid)
        if lease is None:
            target = self._spilled.get(oid)
            if target is not None and "://" not in target:
                # local spill file: serve the chunk stream STRAIGHT
                # from the blob — no arena allocation, no restore (a
                # restore under pressure would evict/spill warm
                # objects just to feed a remote reader).  The open fd
                # guards the blob: an owner free may unlink the path
                # mid-transfer, the inode survives until pull_end.
                try:
                    fd = os.open(target, os.O_RDONLY)
                except OSError:
                    return None
                try:
                    size = self._spilled_sizes.get(oid) \
                        or os.fstat(fd).st_size
                    serves = conn.context.setdefault("spill_serves", {})
                    stale = serves.pop(oid, None)
                    if stale is not None:  # duplicate start on this link
                        os.close(stale[0])
                    serves[oid] = (fd, size)
                except BaseException:
                    # fstat on a truncated blob (or a bad stale fd) must
                    # not leak the fresh fd until process exit
                    os.close(fd)
                    raise
                return {"size": size, "spilled": True}
            if target is not None and await self._restore_from_spill(oid):
                lease = self.store.lease(oid)
        if lease is not None:
            leases = conn.context.setdefault("pull_leases", set())
            if oid in leases:
                # duplicate start on this link: keep a single pin so
                # pull_end / disconnect cleanup stays balanced
                self.store.release(oid)
            else:
                leases.add(oid)
            # cache {offset,size} for the whole transfer: chunk serving
            # then reads straight from the arena without re-taking the
            # store lease per chunk (the pin above keeps it valid)
            conn.context.setdefault("pull_offsets", {})[oid] = lease
            # arena coordinates let a same-host puller copy through
            # shared memory instead of the socket (the pin still
            # guards the range until pull_end)
            return {"size": lease[1], "offset": lease[0],
                    "store_path": self.store.path,
                    "capacity": self.store_capacity}
        inflight = self._inflight_pulls.get(oid)
        if inflight is not None and not inflight.failed:
            # in-progress copy: serve as a *partial* source — chunk
            # requests wait (bounded) for this node's own transfer to
            # produce the range (wait-and-chain broadcast)
            return {"size": inflight.size, "partial": True}
        return None

    async def handle_object_pull_chunk(self, conn, data):
        oid = ObjectID(data["object_id"])
        start = data["offset"]
        n = data["n"]
        # failpoint: the source dies mid-transfer (chaos: striped pulls
        # must fail over to the surviving sources)
        if _fp.active():
            await _fp.afailpoint("raylet.pull_chunk.serve")
        if start < 0 or n <= 0:
            return None
        spill_serve = (conn.context.get("spill_serves") or {}).get(oid)
        if spill_serve is not None:
            fd, size = spill_serve
            if start + n > size:
                return None
            # positioned read in the executor: a cold 5 MiB disk read
            # must not stall every other RPC this raylet serves
            payload = await asyncio.get_running_loop().run_in_executor(
                None, os.pread, fd, n, start)
            return payload if len(payload) == n else None
        entry = (conn.context.get("pull_offsets") or {}).get(oid)
        if entry is not None:
            offset, size = entry
            if start + n <= size:
                # out-of-band payload: the chunk travels as raw frame
                # bytes straight from the arena view to the socket — no
                # bytes() copy, no pickle copy.  Safe because the
                # pull_start pin is held and the frame is queued before
                # this handler yields.
                return rpc.OobPayload(
                    {"n": n}, self.store.view(offset + start, n))
            return None
        inflight = self._inflight_pulls.get(oid)
        if inflight is not None:
            ok = await inflight.wait_range(
                start, n,
                getattr(self.config, "object_transfer_chunk_timeout_s",
                        30.0))
            # serve from the in-progress copy only while its transfer
            # still OWNS the block (entry present and not failed): a
            # just-sealed copy is unpinned/evictable, so the sealed
            # case must go through the pinning lease path below
            if ok and not inflight.failed and start + n <= inflight.size \
                    and self._inflight_pulls.get(oid) is inflight:
                return bytes(self.store.view(inflight.offset + start, n))
            # fall through: the transfer may have sealed (serve from the
            # store) or failed (lease below misses -> None)
        lease = self.store.lease(oid)
        if lease is None:
            return None
        try:
            offset, size = lease
            if start + n > size:
                return None
            return bytes(self.store.view(offset + start, n))
        finally:
            self.store.release(oid)

    async def handle_object_pull_end(self, conn, data):
        oid = ObjectID(data["object_id"])
        leases = conn.context.get("pull_leases", set())
        if oid in leases:
            leases.discard(oid)
            (conn.context.get("pull_offsets") or {}).pop(oid, None)
            self.store.release(oid)
        serve = (conn.context.get("spill_serves") or {}).pop(oid, None)
        if serve is not None:
            os.close(serve[0])
        return True

    async def handle_object_release(self, conn, data):
        for b in data["object_ids"]:
            self.store.release(ObjectID(b))
        return True

    async def handle_object_contains(self, conn, data):
        oid = ObjectID(data["object_id"])
        return self.store.contains(oid) or oid in self._spilled

    async def handle_object_free(self, conn, data):
        """Owner-driven free: drop primaries, spill files, local copies."""
        for b in data["object_ids"]:
            oid = ObjectID(b)
            inflight = self._inflight_pulls.get(oid)
            if inflight is not None:
                # freeing mid-pull: fail the transfer and let ITS
                # cleanup delete the create once every writer stopped —
                # deleting here would free the block under in-flight
                # chunk writes and corrupt whatever reuses it
                inflight.fail()
                self._owner_of.pop(oid, None)
                continue
            if oid in self._primary:
                self._primary.discard(oid)
                self.store.release(oid)
            target = self._spilled.pop(oid, None)
            if target:
                self._spill_bytes -= self._spilled_sizes.pop(oid, 0)
                # executor-side: a URI-tier delete is a network call
                # that must not stall this event loop (local unlinks
                # ride along for uniformity)
                await asyncio.get_running_loop().run_in_executor(
                    None, self._delete_spill_blob, target)
            entry = self._restoring.get(oid)
            if entry is not None:
                # an executor thread is writing this object's arena
                # block right now: deleting would free the unsealed
                # pin-0 entry instantly and the write would scribble
                # over whatever re-allocates it — flag the restores to
                # complete the delete on the last guard-exit
                entry[1] = True
            else:
                self.store.delete(oid)
            self._owner_of.pop(oid, None)
        return True

    async def handle_store_info(self, conn, data):
        """Connection bootstrap info for late-joining drivers."""
        return {"store_path": self.store.path,
                "store_capacity": self.store_capacity,
                "session_dir": self.session_dir,
                "node_id": self.node_id.binary()}

    async def handle_store_stats(self, conn, data):
        try:
            stats = self.store.stats_ex()
        except Exception:  # noqa: BLE001 — older .so without stats_ex
            stats = self.store.stats()
        stats["num_primary"] = len(self._primary)
        stats["num_spilled"] = len(self._spilled)
        stats["spill_bytes"] = self._spill_bytes
        return stats

    # ------------------------------------------------------------------
    # spilling (LocalObjectManager)
    # ------------------------------------------------------------------
    async def _maybe_spill(self, incoming: int) -> None:
        """Spill cold sealed primaries to the disk tier under arena
        pressure.

        Selection is LRU by LAST PIN from the native store's spill
        queue (``spill_candidates`` with max_pins=1: the raylet's own
        primary pin — a client-pinned or unsealed object can never be
        picked).  Blob writes run in the executor with the object's
        lease held and commit via rename, so a write that dies
        mid-flight never leaves a half file claiming to be a valid
        blob and the in-store copy survives every failure mode.  One
        sweep runs at a time; concurrent creates ride their own retry
        loop while it makes room."""
        cfg = self.config
        frac = getattr(cfg, "object_spill_threshold", -1.0)
        if frac is None or frac < 0:
            frac = cfg.object_spilling_threshold
        threshold = frac * self.store_capacity
        # lock-free pressure probe: this runs on EVERY create/pull
        # allocation — stats() would sweep all shard mutexes (and
        # inflate the contention counters) just to count objects
        if self.store.used() + incoming <= threshold:
            return
        if self._spill_lock is None:
            self._spill_lock = asyncio.Lock()
        async with self._spill_lock:
            used = self.store.used()
            if used + incoming <= threshold:
                return  # the sweep we waited on already made room
            await self._spill_sweep(used + incoming - int(threshold))

    async def _spill_for_fragmentation(self, need: int) -> None:
        """An allocation failed while accounting says there is room:
        the free space exists but no single run fits (fragmentation —
        long-lived primaries pinning stripe-region starts).  Spill
        ``need`` bytes of the coldest primaries regardless of the
        pressure threshold; a spilled block's region becomes one
        contiguous free run.  Shares ``_spill_lock`` with the pressure
        sweeps, so at most one sweep runs at a time."""
        if self._spill_lock is None:
            self._spill_lock = asyncio.Lock()
        async with self._spill_lock:
            await self._spill_sweep(need)

    async def _spill_sweep(self, need: int) -> None:
        cfg = self.config
        spill_uri = cfg.object_spilling_uri
        max_bytes = getattr(cfg, "object_spill_max_bytes", 0)
        loop = asyncio.get_running_loop()
        spilled = 0
        candidates = self.store.spill_candidates(max_ids=256, max_pins=1)
        if candidates is None:
            # stale .so without the spill queue: fall back to the old
            # behavior — primaries in table order, sizes learned from
            # the lease below (0 here skips only the pre-lease cap
            # check; the post-lease one still applies)
            candidates = [(o, 0) for o in list(self._primary)]
        # owners whose commit RPC failed THIS sweep: skip their other
        # objects instead of burning a timeout each — the sweep runs
        # under _spill_lock, which concurrent creates wait on against
        # their own 30 s deadline
        dead_owners: set = set()
        for oid, size in candidates:
            if spilled >= need:
                break
            if oid not in self._primary or oid in self._spilled:
                continue  # secondary copies just evict; never re-spill
            if self._owner_of.get(oid) in dead_owners:
                continue  # unreachable owner: nothing to commit to
            if max_bytes and self._spill_bytes + size > max_bytes:
                logger.warning(
                    "spill tier at object_spill_max_bytes cap (%d); "
                    "arena pressure will surface as store-full", max_bytes)
                break
            lease = self.store.lease(oid)
            if lease is None:
                self._primary.discard(oid)  # raced away
                continue
            offset, lsize = lease
            if max_bytes and self._spill_bytes + lsize > max_bytes:
                self.store.release(oid)
                break  # true size known only post-lease on the fallback
            # snapshot the owner before the commit await: a concurrent
            # free can pop _owner_of mid-RPC, and a None slipped into
            # dead_owners would match every OWNERLESS later candidate
            owner = self._owner_of.get(oid)
            try:
                view = self.store.view(offset, lsize)
                if spill_uri:
                    # external tier: the blob outlives this node, and
                    # the owner learns the URI so ANY node can restore
                    # (parity: reference external_storage.py)
                    from ray_tpu.air import storage as air_storage
                    uri = air_storage.join(spill_uri, oid.hex())
                    await loop.run_in_executor(
                        None, self._write_spill_uri, uri, view)
                    # two-phase commit: the in-store copy is only
                    # dropped once the OWNER has durably recorded the
                    # blob — a fire-and-forget notify raced node death
                    # (blob written, owner ignorant: the object was
                    # unrestorable AND its blob leaked on free)
                    if not await self._commit_spill_to_owner(oid,
                                                             uri=uri):
                        if owner is not None:
                            dead_owners.add(owner)
                        await loop.run_in_executor(
                            None, self._delete_spill_blob, uri)
                        self.store.release(oid)
                        continue
                    self._spilled[oid] = uri
                else:
                    path = os.path.join(self._spill_dir, oid.hex())
                    await loop.run_in_executor(
                        None, self._write_spill_file, path, view)
                    # local tier: the owner records the NODE so remote
                    # pulls route here and stream from the spill file
                    addr = getattr(self.server, "address", None)
                    if addr and not await self._commit_spill_to_owner(
                            oid, node=list(addr)):
                        if owner is not None:
                            dead_owners.add(owner)
                        await loop.run_in_executor(
                            None, self._delete_spill_blob, path)
                        self.store.release(oid)
                        continue
                    self._spilled[oid] = path
            except Exception:  # noqa: BLE001 — spill tier down: keep
                # the in-store copy (primary pin stays; only the lease
                # taken above is dropped)
                logger.exception("spill of %s failed; keeping in-store",
                                 oid.hex()[:12])
                self.store.release(oid)
                continue
            if not self.store.contains(oid):
                # the owner freed the object while the blob was being
                # written (our lease doomed the delete): registering the
                # spill now would resurrect a freed object and leak its
                # blob — discard and let the release complete the free
                target = self._spilled.pop(oid, None)
                if target is not None:
                    await loop.run_in_executor(
                        None, self._delete_spill_blob, target)
                self.store.release(oid)
                continue
            self._spilled_sizes[oid] = lsize
            self._spill_bytes += lsize
            _tm.store_spilled(lsize)
            # per-job attribution: the owner job rides inside the id
            # (ObjectID -> TaskID -> JobID lineage encoding)
            _tm.job_spilled_bytes(oid.job_id().hex(), lsize)
            self.store.release(oid)  # the lease taken above
            self._primary.discard(oid)
            self.store.release(oid)  # drop the primary pin
            self.store.delete(oid)
            spilled += lsize

    def _write_spill_file(self, path: str, view) -> None:
        """Executor-side blob write: tmp file + rename commit, so a
        failure (or kill) mid-write never publishes a torn blob."""
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                half = len(view) // 2
                f.write(view[:half])
                # failpoint: the spill write dies mid-flight (chaos) —
                # the half-written tmp must be discarded, never adopted
                _spill_write_failpoint()
                f.write(view[half:])
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _write_spill_uri(self, uri: str, view) -> None:
        _spill_write_failpoint()
        from ray_tpu.air import storage as air_storage
        air_storage.write_bytes(uri, bytes(view))

    async def _commit_spill_to_owner(self, oid: ObjectID,
                                     uri: Optional[str] = None,
                                     node: Optional[list] = None) -> bool:
        """Record the blob's location with the owner — a URI (restores
        anywhere, survives this node) or this node's address (local
        spill file; pulls stream straight from it).  The sweep only
        drops the in-store copy on True; an unowned object (no owner
        recorded — e.g. a restored secondary) commits trivially."""
        owner = self._owner_of.get(oid)
        if owner is None:
            return True
        try:
            conn = await self.pool.get((owner[1], owner[2]))
            payload: Dict[str, Any] = {"object_id": oid.binary()}
            if uri is not None:
                payload["uri"] = uri
            if node is not None:
                payload["node"] = node
            # short timeout: the sweep holds _spill_lock, which
            # concurrent creates wait on against their own deadline —
            # a black-holed owner must not stall the whole arena
            await conn.call("object_spilled", payload, timeout=3.0)
            return True
        except Exception:  # noqa: BLE001 — owner unreachable: the
            return False   # caller keeps the in-store copy

    def _delete_spill_blob(self, target: str) -> None:
        try:
            if "://" in target:
                from ray_tpu.air import storage as air_storage
                air_storage.delete(target)
            else:
                os.unlink(target)
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    async def _restore_from_spill(self, oid: ObjectID) -> bool:
        """Transparent restore: read the spilled blob back into the
        arena and seal it (unpinned — a restored copy just evicts; its
        blob stays in the tier until the owner frees the object)."""
        target = self._spilled.get(oid)
        if target is None:
            return False
        if "://" in target:
            return await self._restore_from_uri(oid, target)
        try:
            size = os.path.getsize(target)
        except OSError:
            return False
        # guard entered before the FIRST await — see _finish_restore
        self._restore_guard_enter(oid)
        return await self._finish_restore(
            oid, size, target,
            lambda offset, view: self._read_spill_file(target, view))

    async def _restore_from_uri(self, oid: ObjectID, uri: str) -> bool:
        """Restore a URI-spilled blob — works on ANY node, including
        ones that never held the object (the spiller may be dead)."""
        loop = asyncio.get_running_loop()
        # the guard must span the blob READ too: a free landing while
        # the read runs deletes the (not-yet-existing) arena entry as a
        # no-op — sealing the already-read bytes afterwards would
        # resurrect the freed object as an undeletable zombie
        self._restore_guard_enter(oid)
        try:
            data = await loop.run_in_executor(
                None, self._read_spill_uri, uri)
        except Exception:  # noqa: BLE001 — missing/unreachable tier
            if self._restore_guard_exit(oid):
                self.store.delete(oid)
            return False
        if self._restoring[oid][1]:
            # freed during the read; its blob is already deleted
            if self._restore_guard_exit(oid):
                self.store.delete(oid)
            return False
        return await self._finish_restore(
            oid, len(data), uri,
            lambda offset, view: self.store.write_range(offset, data))

    def _restore_guard_enter(self, oid: ObjectID) -> None:
        ent = self._restoring.get(oid)
        if ent is None:
            ent = self._restoring[oid] = [0, False]
        ent[0] += 1

    def _restore_guard_exit(self, oid: ObjectID) -> bool:
        """Drop one restore's guard; True when this was the LAST guard
        out AND a free arrived mid-restore — the caller then completes
        the deferred delete (earlier exiters must not: a sibling's
        executor thread may still own the block)."""
        ent = self._restoring[oid]
        ent[0] -= 1
        if ent[0] > 0:
            return False
        del self._restoring[oid]
        return ent[1]

    async def _finish_restore(self, oid: ObjectID, size: int,
                              target: str, writer) -> bool:
        """Allocate + executor-write + seal under the freed-mid-restore
        discipline.  The caller has ALREADY entered ``_restoring[oid]``
        (before its first await): handle_object_free must never
        store.delete an oid whose arena block an executor thread may be
        writing — it flags the entry instead and the last guard-exit
        here completes the deferred delete.  Every path out drops the
        guard exactly once."""
        ok = False
        try:
            try:
                # restoring may itself need room: spill colder objects
                # first so larger-than-arena working sets rotate through
                await self._maybe_spill(size)
                offset, view = self.store.alloc(oid, size)
            except ValueError:
                return self.store.contains(oid)  # concurrently restored
            except Exception:  # noqa: BLE001 — full even after spilling
                return False
            loop = asyncio.get_running_loop()
            try:
                # GIL-releasing write off the event loop (restored
                # blobs can be arena-sized)
                await loop.run_in_executor(None, writer, offset, view)
            except Exception:  # noqa: BLE001 — unreadable blob: drop
                # the create so the id isn't stuck half-restored
                logger.exception("restore of %s from %s failed",
                                 oid.hex()[:12], target)
                self.store.delete(oid)
                return False
            # seal before the guard drops: if a free raced in, the
            # guard-exit below deletes the (briefly sealed) copy
            self.store.seal(oid)
            ok = True
        finally:
            if self._restore_guard_exit(oid):
                # freed while the restore ran: complete the deferred
                # delete now that no executor thread owns the block
                self.store.delete(oid)
                ok = False
        if ok:
            _tm.store_restored(size)
            return True
        return False

    def _read_spill_file(self, path: str, view) -> None:
        # failpoint: the restore read fails (chaos) — the caller must
        # surface a miss, not a torn object
        _restore_read_failpoint()
        with open(path, "rb") as f:
            f.readinto(view)

    def _read_spill_uri(self, uri: str) -> bytes:
        _restore_read_failpoint()
        from ray_tpu.air import storage as air_storage
        return air_storage.read_bytes(uri)
