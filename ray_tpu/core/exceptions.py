"""Exception hierarchy surfaced by the public API.

Parity: reference ``python/ray/exceptions.py``.  Errors that happen inside a
remote task are captured, serialized, and re-raised at ``get`` time wrapped
in :class:`TaskError`, preserving the remote traceback as text.
"""

from __future__ import annotations

import traceback


class RayTpuError(Exception):
    """Base class for all framework errors."""


class TaskError(RayTpuError):
    """A remote task raised an exception; re-raised at ``get`` time.

    Carries the remote traceback as formatted text (the remote frames are
    from another process and cannot be re-materialized).
    """

    def __init__(self, cause: BaseException | None, remote_traceback: str = "",
                 task_desc: str = ""):
        self.cause = cause
        self.remote_traceback = remote_traceback
        self.task_desc = task_desc
        super().__init__(str(cause))

    def __str__(self) -> str:
        out = f"Task {self.task_desc} failed: {self.cause!r}"
        if self.remote_traceback:
            out += "\n--- remote traceback ---\n" + self.remote_traceback
        return out

    @classmethod
    def from_exception(cls, exc: BaseException, task_desc: str = "") -> "TaskError":
        tb = "".join(traceback.format_exception(type(exc), exc, exc.__traceback__))
        return cls(exc, tb, task_desc)


class TaskCancelledError(TaskError):
    """The task was cancelled via ``ray_tpu.cancel`` (parity: reference
    ``python/ray/exceptions.py`` TaskCancelledError).  Raised by ``get``
    on any of the task's return refs.  Subclasses TaskError so the
    owner-side failure plumbing publishes it verbatim and ``get``
    re-raises this exact type."""

    def __init__(self, task_desc: str = ""):
        super().__init__(None, "", task_desc)

    def __str__(self) -> str:
        return f"Task {self.task_desc or '<unknown>'} was cancelled"


class ActorError(TaskError):
    """An actor task failed or the actor died before/while executing it."""


class ActorDiedError(ActorError):
    def __init__(self, actor_desc: str = "", reason: str = ""):
        super().__init__(None, "", actor_desc)
        self.reason = reason

    def __str__(self) -> str:
        return f"Actor {self.task_desc} died: {self.reason}"


class WorkerCrashedError(RayTpuError):
    """The worker process executing the task died unexpectedly."""


class ObjectLostError(RayTpuError):
    """An object's value was lost (all copies evicted or node died) and
    could not be reconstructed from lineage."""

    def __init__(self, object_id_hex: str, reason: str = ""):
        self.object_id_hex = object_id_hex
        super().__init__(f"Object {object_id_hex} lost: {reason}")


class ObjectStoreFullError(RayTpuError):
    """Allocation failed even after eviction and spilling."""


class GetTimeoutError(RayTpuError, TimeoutError):
    """``get(..., timeout=)`` expired before the object was available."""


class RuntimeEnvSetupError(RayTpuError):
    """Preparing a task/actor runtime environment failed."""


class NodeDiedError(RayTpuError):
    """The node hosting the computation was declared dead."""


class PlacementGroupUnschedulableError(RayTpuError):
    """No feasible placement for the requested bundles."""


class RayTpuSystemError(RayTpuError):
    """Internal invariant violation; indicates a framework bug."""


class ActorExitRequest(BaseException):
    """Raised by :func:`ray_tpu.actor.exit_actor`; BaseException so a
    user-level ``except Exception`` inside the method cannot swallow
    the exit (parity: the reference signals via a SystemExit path)."""
