"""Task specifications — the unit handed from submitter to executor.

Parity: reference ``src/ray/common/task/task_spec.h`` /
``src/ray/protobuf/common.proto`` TaskSpec.  A spec fully describes one
invocation: function identity (by hash into the GCS function table),
serialized arguments (small values inlined; larger ones as ObjectRef
references), resource demand, retry policy, and — for actor tasks —
ordering metadata.

Wire/snapshot compatibility: spec pickles are SAME-VERSION artifacts —
every process in a cluster (and the GCS snapshot a restarted head
reads) runs the same code.  The ``slots=True`` dataclasses therefore
do not carry cross-version pickle shims; a rolling-upgrade story would
need a versioned codec here first (the reference takes the same
same-version stance for its protobuf-fields-at-head wire format).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core.ids import ActorID, JobID, ObjectID, PlacementGroupID, TaskID
from ray_tpu.core.object_ref import OwnerAddress


class TaskType(enum.Enum):
    NORMAL_TASK = 0
    ACTOR_CREATION_TASK = 1
    ACTOR_TASK = 2


@dataclass(slots=True)
class TaskArg:
    """Either an inlined serialized value or an object reference."""

    # Exactly one of (value_bytes, object_id) is set.
    value_bytes: Optional[bytes] = None
    object_id: Optional[ObjectID] = None
    owner_address: Optional[OwnerAddress] = None
    # ObjectRefs nested INSIDE an inlined value (e.g. a dict of refs):
    # pinned as submitted-refs for the task's flight so the owner cannot
    # free them before the borrowing worker registers (parity: the
    # reference's borrowing protocol pins args until execution).
    contained_ids: List[ObjectID] = field(default_factory=list)

    def is_inline(self) -> bool:
        return self.value_bytes is not None


@dataclass(slots=True)
class SchedulingStrategy:
    """Default / spread / node-affinity / placement-group placement.

    Parity: ``python/ray/util/scheduling_strategies.py``.
    """

    kind: str = "DEFAULT"  # DEFAULT | SPREAD | NODE_AFFINITY | PLACEMENT_GROUP
    node_id_hex: Optional[str] = None
    soft: bool = False
    placement_group_id: Optional[PlacementGroupID] = None
    bundle_index: int = -1
    capture_child_tasks: bool = False


@dataclass(slots=True)
class TaskSpec:
    task_id: TaskID
    job_id: JobID
    task_type: TaskType
    # Identity of the function/class in the GCS function table.
    function_id: str
    function_descriptor: str  # human-readable "module.fn" for errors/state API
    args: List[TaskArg] = field(default_factory=list)
    num_returns: int = 1
    resources: Dict[str, float] = field(default_factory=dict)
    max_retries: int = 0
    retry_exceptions: bool = False
    #: worker recycling: after this many executions of this function the
    #: worker exits and a fresh one serves the next call (0 = unlimited;
    #: reference remote_function.py:58 — and like its num_gpus rule,
    #: TPU-resource tasks default to 1 so device memory is released)
    max_calls: int = 0
    scheduling_strategy: SchedulingStrategy = field(default_factory=SchedulingStrategy)
    owner_address: Optional[OwnerAddress] = None
    # Actor-related fields.
    actor_id: Optional[ActorID] = None
    actor_creation_spec: Optional["ActorCreationSpec"] = None
    # Ordering for actor tasks (per-caller sequence number).
    sequence_number: int = 0
    # Name of the concurrency group for async actors ("" = default).
    concurrency_group: str = ""
    # Runtime environment (env_vars/working_dir/py_modules, packaged) —
    # part of the scheduling key: workers are dedicated per env.
    runtime_env: Optional[Dict[str, Any]] = None
    runtime_env_hash: Optional[str] = None
    # W3C traceparent carrier (opt-in tracing; util/tracing)
    trace_context: Optional[Dict[str, str]] = None
    # Attempt counter (incremented on retries) — return object IDs stay
    # stable across attempts, matching the reference's semantics.
    attempt_number: int = 0
    # Depth in the lineage tree (driver = 0), bounds reconstruction.
    depth: int = 0
    # num_returns="dynamic" (parity: _raylet.pyx:603-622): the task
    # yields a variable number of objects; its single declared return
    # resolves to an ObjectRefGenerator over them.
    dynamic_returns: bool = False
    # num_returns="streaming": dynamic AND each yielded object is
    # pushed to the owner AS PRODUCED, so the caller's generator can
    # consume item i while the task still computes item i+1 (parity:
    # the reference's streaming ObjectRefGenerator protocol).
    stream_returns: bool = False

    def return_ids(self) -> List[ObjectID]:
        return [
            ObjectID.for_task_return(self.task_id, i + 1)
            for i in range(self.num_returns)
        ]

    def dynamic_return_id(self, i: int) -> ObjectID:
        """ID of the i-th yielded object of a dynamic-returns task.
        Index space starts after the declared returns (index 1 is the
        generator handle), and is attempt-independent so lineage
        reconstruction regenerates the same IDs."""
        return ObjectID.for_task_return(self.task_id,
                                        self.num_returns + 1 + i)

    def scheduling_key(self) -> Tuple:
        """Tasks with the same key can share leased workers (parity:
        ``SchedulingKey`` in direct_task_transport.h)."""
        strat = self.scheduling_strategy
        return (
            self.function_id,
            tuple(sorted(self.resources.items())),
            strat.kind,
            strat.node_id_hex,
            strat.placement_group_id,
            strat.bundle_index,
            self.runtime_env_hash,
        )

    def debug_name(self) -> str:
        return f"{self.function_descriptor}[{self.task_id.hex()[:12]}]"


@dataclass(slots=True)
class ActorCreationSpec:
    max_restarts: int = 0
    max_task_retries: int = 0
    name: Optional[str] = None  # named (and optionally detached) actors
    namespace: str = "default"
    lifetime_detached: bool = False
    max_concurrency: int = 1
    is_asyncio: bool = False
    concurrency_groups: Dict[str, int] = field(default_factory=dict)
