"""Object store layers: native shared-memory store + in-process memory store.

Parity map (reference):
- ``SharedMemoryStore``  -> plasma store, owned by the raylet
  (``src/ray/object_manager/plasma/store.h``); here a thin wrapper over the
  C++ library in ``src/object_store.cc``.
- ``StoreClient``        -> plasma client (``plasma/client.cc``); workers
  mmap the raylet's arena file and turn {offset,size} leases into zero-copy
  memoryviews.
- ``MemoryStore``        -> the core worker's in-process store for small /
  inlined objects (``core_worker/store_provider/memory_store/memory_store.h``).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.core import native
from ray_tpu.core.exceptions import ObjectStoreFullError
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.serialization import SerializedObject


class SharedMemoryStore:
    """Raylet-side owner of the shm arena (C++ allocator + LRU)."""

    def __init__(self, path: str, capacity: int, shards: int = 0):
        """``shards`` stripes the C++ metadata table (0 = library
        default): N concurrent writers doing create/seal/get/release
        only contend when their object ids hash to the same shard."""
        self._lib = native.load()
        create_sharded = getattr(self._lib, "rtpu_store_create_sharded",
                                 None)
        if create_sharded is not None:
            self._handle = create_sharded(path.encode(), capacity,
                                          max(0, int(shards)))
        else:  # stale pre-built .so
            self._handle = self._lib.rtpu_store_create(path.encode(),
                                                       capacity)
        if not self._handle:
            raise OSError(f"failed to create object store at {path}")
        self.path = path
        self.capacity = capacity
        self._mm = _map_file(path, capacity)
        self._view = memoryview(self._mm)
        # Pre-fault the arena in the background: tmpfs pages materialize
        # on FIRST touch, which otherwise lands in some client's timed
        # copy (first-touch faults halved large-put bandwidth).  Faulted
        # once here, every process mapping the file takes only cheap
        # minor faults (parity motivation: plasma pre-allocates its shm
        # pool via dlmalloc at store boot).
        self._closed = False
        # lazily-created base address for GIL-releasing range writes
        self._base_addr: Optional[int] = None
        self._base_export = None
        self._prefault_thread = threading.Thread(
            target=self._prefault, name="rtpu-prefault", daemon=True)
        self._prefault_thread.start()

    #: prefault at most this much (first-fit allocation reuses the low
    #: arena, so the head of the file is where puts land), in small
    #: chunks at a <=20% duty cycle, starting only after the boot
    #: window: populating a multi-GB arena flat-out starved a 1-core
    #: host long enough to trip cluster health checks
    _PREFAULT_CAP = 2 * 1024 ** 3
    _PREFAULT_CHUNK = 64 * 1024 * 1024
    _PREFAULT_DELAY_S = 10.0

    def _prefault(self) -> None:
        import time as time_mod

        # sleep through node bring-up (the CPU-contended window), in
        # small slices so close() never waits long on the join
        deadline = time_mod.monotonic() + self._PREFAULT_DELAY_S
        while time_mod.monotonic() < deadline:
            if self._closed:
                return
            time_mod.sleep(0.2)
        try:
            # MADV_POPULATE_WRITE (=23, Linux 5.14+; the mmap module
            # doesn't expose the constant yet, so call madvise
            # directly).  It only materializes pages — never alters
            # content — so it is safe alongside live allocations.
            arr = ctypes.c_char.from_buffer(self._mm)
            try:
                libc = ctypes.CDLL(None, use_errno=True)
                base = ctypes.addressof(arr)
                # populated pages are COMMITTED tmpfs RAM whether or not
                # the arena is ever used — bound by what the host can
                # spare (multi-node test clusters run many stores on one
                # box), not just the flat cap
                total = min(self.capacity, self._PREFAULT_CAP,
                            _mem_available() // 8)
                for off in range(0, total, self._PREFAULT_CHUNK):
                    if self._closed:
                        return
                    n = min(self._PREFAULT_CHUNK, total - off)
                    t0 = time_mod.monotonic()
                    if libc.madvise(ctypes.c_void_p(base + off),
                                    ctypes.c_size_t(n), 23) != 0:
                        return  # unsupported kernel: stay lazy
                    # <=20% duty cycle: page population is kernel-side
                    # CPU burn that would otherwise starve event loops
                    # on small hosts.  Sleep in small slices re-checking
                    # _closed: one long sleep after a slow madvise could
                    # exceed close()'s 2 s join timeout, leaving this
                    # thread madvising a mapping close() is tearing down
                    pause = 4 * (time_mod.monotonic() - t0) + 0.01
                    end = time_mod.monotonic() + pause
                    while time_mod.monotonic() < end:
                        if self._closed:
                            return
                        time_mod.sleep(0.05)
            finally:
                del arr  # release the buffer export before any close()
        except (IndexError, ValueError, OSError):
            pass  # store closed mid-prefault (or madvise unsupported)

    # -- producer side ----------------------------------------------------
    def alloc(self, object_id: ObjectID, size: int,
              hint: int = 0) -> Tuple[int, memoryview]:
        """Allocate space for the object; returns (offset, writable view).

        ``hint`` keys the allocator's per-client slab bucket: allocations
        with the same hint reuse blocks that hint freed before, so a
        producing process keeps writing through warm page-table entries
        (on fault-expensive hosts a cold 64 MiB write runs ~10x slower
        than a warm one).  0 = the raylet's own bucket (restores, pulls).
        """
        rc = self._lib.rtpu_store_put_hint(
            self._handle, object_id.binary(), size, hint)
        if rc == -2:
            raise ValueError(f"object {object_id.hex()} already exists")
        if rc < 0:
            raise ObjectStoreFullError(
                f"cannot allocate {size} bytes (capacity {self.capacity})"
            )
        return rc, self._view[rc : rc + size]

    def create(self, object_id: ObjectID, size: int,
               hint: int = 0) -> memoryview:
        return self.alloc(object_id, size, hint)[1]

    def seal(self, object_id: ObjectID) -> None:
        self._lib.rtpu_store_seal(self._handle, object_id.binary())

    def put_serialized(self, object_id: ObjectID, obj: SerializedObject) -> int:
        size = obj.total_size()
        buf = self.create(object_id, size)
        obj.write_to(buf)
        self.seal(object_id)
        return size

    def put_raw(self, object_id: ObjectID, data: bytes) -> int:
        buf = self.create(object_id, len(data))
        buf[:] = data
        self.seal(object_id)
        return len(data)

    # -- consumer side ----------------------------------------------------
    def lease(self, object_id: ObjectID) -> Optional[Tuple[int, int]]:
        """Pin the object; returns (offset, size) or None. Caller must
        eventually call release()."""
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        ok = self._lib.rtpu_store_get(
            self._handle, object_id.binary(), ctypes.byref(off), ctypes.byref(size)
        )
        return (off.value, size.value) if ok else None

    def view(self, offset: int, size: int) -> memoryview:
        return self._view[offset : offset + size]

    def _ensure_base_addr(self) -> int:
        """Arena base address for ctypes memmoves (the export must be
        dropped before ``close()`` unmaps — see close())."""
        if self._closed:
            raise ValueError("store is closed")
        if self._base_addr is None:
            self._base_export = ctypes.c_char.from_buffer(self._mm)
            self._base_addr = ctypes.addressof(self._base_export)
        return self._base_addr

    def write_range(self, offset: int, data) -> None:
        """Copy ``data`` (bytes-like) into the arena at ``offset`` with a
        GIL-releasing ``ctypes.memmove``.  Pull transfers run this in an
        executor thread: on fault-expensive hosts a cold 5 MiB chunk
        write stalls ~15 ms, which would otherwise freeze the raylet
        event loop (and with it every lease/heartbeat) for the duration
        of an incoming transfer."""
        base = self._ensure_base_addr()
        n = len(data)
        if isinstance(data, (bytearray, memoryview)):
            # ctypes only auto-converts bytes; take the buffer address
            # (zero-copy) for the writable bytes-likes
            src = ctypes.addressof(ctypes.c_char.from_buffer(data))
            ctypes.memmove(base + offset, src, n)
        else:
            ctypes.memmove(base + offset, data, n)

    def copy_in(self, offset: int, src_addr: int, n: int) -> None:
        """memmove from a foreign address (e.g. another raylet's mapped
        arena) into this arena — GIL-releasing, executor-friendly (the
        same-host shm transfer fast path)."""
        ctypes.memmove(self._ensure_base_addr() + offset, src_addr, n)

    def get_pinned(self, object_id: ObjectID) -> Optional[memoryview]:
        lease = self.lease(object_id)
        if lease is None:
            return None
        return self.view(*lease)

    def release(self, object_id: ObjectID) -> None:
        self._lib.rtpu_store_release(self._handle, object_id.binary())

    def contains(self, object_id: ObjectID) -> bool:
        return bool(self._lib.rtpu_store_contains(self._handle, object_id.binary()))

    def delete(self, object_id: ObjectID) -> bool:
        return bool(self._lib.rtpu_store_delete(self._handle, object_id.binary()))

    def evict(self, bytes_needed: int) -> int:
        return self._lib.rtpu_store_evict(self._handle, bytes_needed)

    def lru_candidates(self, max_ids: int = 64) -> List[ObjectID]:
        out = ctypes.create_string_buffer(ObjectID.SIZE * max_ids)
        n = self._lib.rtpu_store_lru_candidates(self._handle, out, max_ids)
        raw = out.raw
        return [
            ObjectID(raw[i * ObjectID.SIZE : (i + 1) * ObjectID.SIZE])
            for i in range(n)
        ]

    def used(self) -> int:
        """Allocated bytes, lock-free (atomic read in the native
        store) — the per-allocation spill-pressure probe.  stats()
        additionally counts objects, which sweeps every shard mutex."""
        fn = getattr(self._lib, "rtpu_store_used", None)
        if fn is None:
            return self.stats()["used"]
        return fn(self._handle)

    def stats(self) -> Dict[str, int]:
        used = ctypes.c_uint64()
        cap = ctypes.c_uint64()
        num = ctypes.c_uint64()
        self._lib.rtpu_store_stats(
            self._handle, ctypes.byref(used), ctypes.byref(cap), ctypes.byref(num)
        )
        return {"used": used.value, "capacity": cap.value, "num_objects": num.value}

    #: StatsEx value layout (keep in sync with Store::StatsEx)
    _STATS_EX_FIELDS = ("used", "capacity", "num_objects",
                        "doomed_current", "doomed_total",
                        "reuse_hits", "reuse_misses",
                        "active_buckets", "bucket_free_bytes",
                        "metadata_shards", "shard_contention",
                        "alloc_contention", "alloc_stripes")

    def stats_ex(self) -> Dict[str, int]:
        """Arena telemetry: basic stats plus slab-bucket reuse hit/miss
        counters, doomed-object counts, and bucket occupancy (the
        observability half of the per-client allocator)."""
        fn = getattr(self._lib, "rtpu_store_stats_ex", None)
        if fn is None:
            return self.stats()
        out = (ctypes.c_uint64 * len(self._STATS_EX_FIELDS))()
        n = fn(self._handle, out, len(self._STATS_EX_FIELDS))
        return {name: out[i]
                for i, name in enumerate(self._STATS_EX_FIELDS[:n])}

    def spill_candidates(self, max_ids: int = 64, max_pins: int = 1
                         ) -> Optional[List[Tuple[ObjectID, int]]]:
        """Sealed objects whose pin count is at most ``max_pins``,
        oldest last-pin first, as (id, payload size) — the raylet's
        LRU-by-last-pin spill queue (its own primary pin keeps
        pin_count at 1, so max_pins=1 means no client is reading).
        Unsealed and client-pinned objects never appear.  Returns
        None on a stale pre-built .so without the symbol — NOT an
        empty list, and not the unpinned LRU queue (primaries always
        hold the raylet's pin, so an LRU-based answer would make the
        spill sweep silently spill nothing); the caller falls back to
        its own primary table."""
        fn = getattr(self._lib, "rtpu_store_spill_candidates", None)
        if fn is None:
            return None
        ids = ctypes.create_string_buffer(ObjectID.SIZE * max_ids)
        sizes = (ctypes.c_uint64 * max_ids)()
        n = fn(self._handle, ids, sizes, max_ids, max_pins)
        raw = ids.raw
        return [(ObjectID(raw[i * ObjectID.SIZE:(i + 1) * ObjectID.SIZE]),
                 sizes[i]) for i in range(n)]

    def shard_contention(self) -> List[int]:
        """Cumulative contended-lock count per metadata shard."""
        fn = getattr(self._lib, "rtpu_store_shard_contention", None)
        if fn is None:
            return []
        out = (ctypes.c_uint64 * 64)()
        n = fn(self._handle, out, 64)
        return list(out[:n])

    def bucket_occupancy(self) -> List[Tuple[int, int]]:
        """Per-bucket live allocation bytes, nonzero buckets only, as
        (bucket index, bytes) — arena occupancy by producing client."""
        fn = getattr(self._lib, "rtpu_store_bucket_used", None)
        if fn is None:
            return []
        out = (ctypes.c_uint64 * 64)()
        n = fn(self._handle, out, 64)
        return [(i, out[i]) for i in range(n) if out[i]]

    def close(self) -> None:
        if self._handle:
            self._closed = True
            # the prefault thread holds a buffer export on the mmap; let
            # it notice _closed and drop it (chunks are sub-second)
            self._prefault_thread.join(timeout=2.0)
            self._base_addr = None
            self._base_export = None  # drop the write_range buffer export
            self._view.release()
            try:
                self._mm.close()
            except BufferError:
                pass  # prefault export still live; process teardown
            self._lib.rtpu_store_destroy(self._handle)
            self._handle = None
            try:
                os.unlink(self.path)
            except OSError:
                pass


class StoreClient:
    """Worker-side zero-copy view of the raylet's arena file.

    Metadata operations (create/seal/get/release) go through the raylet
    socket; this class only turns granted {offset,size} leases into
    memoryviews over a private mapping of the same file.
    """

    def __init__(self, path: str, capacity: int):
        self.path = path
        self._mm = _map_file(path, capacity)
        self._view = memoryview(self._mm)

    def view(self, offset: int, size: int) -> memoryview:
        return self._view[offset : offset + size]

    def close(self) -> None:
        try:
            self._view.release()
            self._mm.close()
        except BufferError:
            # user code still holds zero-copy arrays over the mapping; the
            # mapping lives until those buffers are garbage collected
            pass


def _mem_available() -> int:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except (OSError, ValueError, IndexError):
        pass
    return 2 * 1024 ** 3  # unknown: assume a small host


def _map_file(path: str, capacity: int) -> mmap.mmap:
    fd = os.open(path, os.O_RDWR)
    try:
        return mmap.mmap(fd, capacity)
    finally:
        os.close(fd)


def map_arena(path: str, capacity: int) -> Tuple[mmap.mmap, int, Any]:
    """Map an existing arena file for direct memmove access (the
    same-host transfer fast path).  Returns ``(mmap, base_address,
    export)``; the caller owns teardown — drop the export reference
    before closing the mmap, or close() raises BufferError."""
    mm = _map_file(path, capacity)
    export = ctypes.c_char.from_buffer(mm)
    return mm, ctypes.addressof(export), export


class MemoryStore:
    """In-process store for small objects, with blocking waiters.

    Values are kept serialized (meta+buffer bytes) so a stored exception or
    cross-process handoff behaves identically to the shm path.
    """

    def __init__(self):
        self._lock = threading.Condition()
        self._objects: Dict[ObjectID, bytes] = {}

    def put(self, object_id: ObjectID, data: bytes) -> None:
        with self._lock:
            self._objects[object_id] = data
            self._lock.notify_all()

    def get(self, object_id: ObjectID) -> Optional[bytes]:
        with self._lock:
            return self._objects.get(object_id)

    def wait(self, object_ids: List[ObjectID], num_returns: int,
             timeout: Optional[float]) -> List[ObjectID]:
        """Block until num_returns of object_ids are present (or timeout)."""
        import time

        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                ready = [o for o in object_ids if o in self._objects]
                if len(ready) >= num_returns:
                    return ready
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return ready
                self._lock.wait(remaining)

    def contains(self, object_id: ObjectID) -> bool:
        with self._lock:
            return object_id in self._objects

    def delete(self, object_id: ObjectID) -> None:
        with self._lock:
            self._objects.pop(object_id, None)

    def size(self) -> int:
        with self._lock:
            return len(self._objects)
