"""Value serialization with zero-copy buffer support.

Parity: reference ``python/ray/_private/serialization.py`` (cloudpickle +
pickle-5 out-of-band buffers, zero-copy numpy reads from plasma).

Wire layout of a serialized object:

    [8B magic+version][4B meta_len][meta pickle][4B n_buffers]
    ([8B len][pad to 64][buffer bytes]) * n_buffers

The metadata pickle is produced with ``cloudpickle`` (protocol 5) using a
``buffer_callback`` so large contiguous buffers (numpy arrays, jax host
arrays, bytes) are extracted out-of-band.  On read, buffers are
reconstructed as memoryviews directly over the shared-memory mapping —
numpy arrays alias store memory with no copy.  Buffers are 64-byte aligned
so the views are friendly to XLA host-buffer donation.

ObjectRefs found inside values are serialized specially so the ownership
layer can track borrowed references (reference ``serialization.py``'s
object-ref hooks); the contained refs are collected into the header.
"""

from __future__ import annotations

import io
import pickle
import struct
import sys
from typing import Any, Callable, List, Optional, Tuple

import cloudpickle

_MAGIC = b"RTPUOBJ1"
_ALIGN = 64

# Sentinel metadata for special object kinds (parity: reference object
# metadata strings like RAW / ACTOR_DIED etc.).
META_EXCEPTION = b"__rtpu_exc__"


#: buffers below this stay in-band (pickle stream); also the fast-
#: path bound for small str/bytes in serialize() — keep in sync
_INBAND_LIMIT = 512


def _pad(n: int) -> int:
    return (n + _ALIGN - 1) // _ALIGN * _ALIGN


class SerializedObject:
    """A serialized value: a metadata blob plus out-of-band buffers."""

    __slots__ = ("meta", "buffers", "contained_refs")

    def __init__(self, meta: bytes, buffers: List, contained_refs: List):
        self.meta = meta
        self.buffers = buffers
        self.contained_refs = contained_refs

    def total_size(self) -> int:
        size = len(_MAGIC) + 4 + len(self.meta) + 4
        for buf in self.buffers:
            size = _pad(size + 8) + memoryview(buf).nbytes
        return size

    def write_to(self, dest: memoryview) -> int:
        """Write the wire format into ``dest``; returns bytes written."""
        offset = 0

        def put(data) -> None:
            nonlocal offset
            n = len(data)
            dest[offset : offset + n] = bytes(data) if not isinstance(
                data, (bytes, bytearray, memoryview)
            ) else data
            offset += n

        put(_MAGIC)
        put(struct.pack("<I", len(self.meta)))
        put(self.meta)
        put(struct.pack("<I", len(self.buffers)))
        for buf in self.buffers:
            view = memoryview(buf).cast("B")
            header_end = offset + 8
            data_start = _pad(header_end)
            put(struct.pack("<Q", view.nbytes))
            # zero pad for determinism
            dest[offset:data_start] = b"\x00" * (data_start - offset)
            offset = data_start
            dest[offset : offset + view.nbytes] = view
            offset += view.nbytes
        return offset

    def to_bytes(self) -> bytes:
        out = bytearray(self.total_size())
        n = self.write_to(memoryview(out))
        return bytes(out[:n])


class _RefAwarePickler(cloudpickle.CloudPickler):
    """CloudPickler that records contained ObjectRefs via persistent_id.

    Defined at module scope — building this class per serialize() call
    (a closure class) measured 63 us per empty-dict serialize, i.e. the
    bulk of the per-task submission cost on the hot path."""

    def __init__(self, sink, buffers: List, contained: List):
        super().__init__(sink, protocol=5,
                         buffer_callback=self._buffer_callback)
        self._oob_buffers = buffers
        self._contained = contained

    def _buffer_callback(self, buf: pickle.PickleBuffer) -> bool:
        view = buf.raw()
        if view.nbytes >= _INBAND_LIMIT:  # tiny buffers travel in-band
            self._oob_buffers.append(view)
            return False  # out-of-band
        return True

    def persistent_id(self, obj):  # noqa: N802 (pickle API name)
        from ray_tpu.core.object_ref import ObjectRef

        if isinstance(obj, ObjectRef):
            self._contained.append(obj)
            return ("rtpu_ref", obj.binary(), obj.owner_address())
        return None


_EMPTY_DICT_WIRE: Any = None
_NONE_WIRE: Any = None


# ---------------------------------------------------------------------------
# zero-copy buffer fast path
# ---------------------------------------------------------------------------

def _rebuild_bytes(buf) -> bytes:
    return bytes(buf)


def _rebuild_bytearray(buf) -> bytearray:
    return bytearray(buf)


def _rebuild_jax_array(shape, dtype, buf):
    import jax  # the putter had jax imported; readers reconstruct lazily
    import numpy as _np

    arr = _np.frombuffer(buf, dtype=dtype).reshape(shape)
    return jax.numpy.asarray(arr)


class _BufferWire:
    """Pickles as ``rebuild(*args, <out-of-band buffer>)``: the payload
    rides as a raw out-of-band buffer next to a few-byte meta pickle,
    never through the pickle stream."""

    __slots__ = ("rebuild", "args", "buf")

    def __init__(self, rebuild: Callable, args: tuple, buf) -> None:
        self.rebuild = rebuild
        self.args = args
        self.buf = buf

    def __reduce__(self):
        return (self.rebuild, (*self.args, pickle.PickleBuffer(self.buf)))


def _serialize_buffer_fast(value: Any) -> Optional["SerializedObject"]:
    """Zero-pickle-copy fast path for flat buffer values.

    Large ``bytes``/``bytearray`` and contiguous numpy / single-device
    CPU jax arrays serialize as a tiny handwritten meta pickle plus the
    payload as an out-of-band buffer, so a plasma put's only copy of
    the data is the final write into the writer's mapped slab — the
    cloudpickle path copies ``bytes`` wholesale into the meta stream,
    and jax arrays additionally densified through an intermediate host
    array.  Returns None when the value doesn't qualify (caller falls
    back to cloudpickle).  Flat buffers cannot contain ObjectRefs, so
    skipping the ref-aware pickler is sound.
    """
    vt = type(value)
    buffers: List = []
    if vt is bytes or vt is bytearray:
        if len(value) < _INBAND_LIMIT:
            return None
        rebuild = _rebuild_bytes if vt is bytes else _rebuild_bytearray
        meta = pickle.dumps(_BufferWire(rebuild, (), value), protocol=5,
                            buffer_callback=buffers.append)
        return SerializedObject(meta, buffers, [])
    np_mod = sys.modules.get("numpy")
    if np_mod is not None and vt is np_mod.ndarray:
        if (value.nbytes < _INBAND_LIMIT or value.dtype.hasobject
                or not (value.flags["C_CONTIGUOUS"]
                        or value.flags["F_CONTIGUOUS"])):
            return None
        # plain pickle (protocol 5): numpy's own reduce extracts the
        # data buffer out-of-band; no CloudPickler / persistent_id
        # traversal on a pure array
        meta = pickle.dumps(value, protocol=5,
                            buffer_callback=buffers.append)
        return SerializedObject(meta, buffers, [])
    jax_mod = sys.modules.get("jax")
    if jax_mod is not None and isinstance(value, jax_mod.Array):
        try:
            if getattr(value, "weak_type", False):
                return None  # jnp.asarray would strengthen the type
            if np_mod is None:
                return None
            devices = value.devices()
            if len(devices) == 1 \
                    and next(iter(devices)).platform == "cpu":
                # single-device CPU: np.asarray aliases the XLA host
                # buffer — zero copies before the arena write
                np_view = np_mod.asarray(value)
            elif getattr(value, "is_fully_addressable", False):
                # DEVICE (non-CPU) or multi-shard arrays: one DMA/
                # gather into a host staging array that then rides
                # out-of-band, instead of the old cloudpickle fallback
                # (device_get + a second wholesale copy into the pickle
                # stream).  KV pages and weight shards take this path.
                np_view = np_mod.ascontiguousarray(
                    jax_mod.device_get(value))
            else:
                return None  # multi-host shards not visible here
        except Exception:  # noqa: BLE001 — any layout oddity: fall back
            return None
        if (np_view is None or np_view.nbytes < _INBAND_LIMIT
                or not np_view.flags["C_CONTIGUOUS"]):
            return None
        # ship the payload as raw uint8 (extended dtypes like bfloat16
        # don't speak the buffer protocol) and reinterpret on rebuild
        meta = pickle.dumps(
            _BufferWire(_rebuild_jax_array,
                        (np_view.shape, np_view.dtype),
                        np_view.reshape(-1).view(np_mod.uint8)),
            protocol=5, buffer_callback=buffers.append)
        return SerializedObject(meta, buffers, [])
    return None


def serialize(value: Any) -> SerializedObject:
    """Serialize ``value``, extracting large buffers out-of-band and
    collecting any contained ObjectRefs."""
    global _EMPTY_DICT_WIRE, _NONE_WIRE
    if value is None:
        # the commonest task return; cache the meta bytes (a fresh
        # SerializedObject each call — serialize_exception mutates .meta)
        if _NONE_WIRE is None:
            sink = io.BytesIO()
            _RefAwarePickler(sink, [], []).dump(None)
            _NONE_WIRE = sink.getvalue()
        return SerializedObject(_NONE_WIRE, [], [])
    if type(value) is dict and not value:
        # every no-kwarg task submission serializes {}; cache the bytes
        if _EMPTY_DICT_WIRE is None:
            sink = io.BytesIO()
            _RefAwarePickler(sink, [], []).dump({})
            _EMPTY_DICT_WIRE = sink.getvalue()
        return SerializedObject(_EMPTY_DICT_WIRE, [], [])
    vt = type(value)
    if vt in (int, float, bool) or (
            vt in (str, bytes) and len(value) < _INBAND_LIMIT):
        # primitives can contain neither ObjectRefs nor out-of-band
        # buffers: plain C pickle, skipping the CloudPickler object +
        # persistent_id traversal (~half the per-call serialize cost on
        # small-result actor storms)
        return SerializedObject(pickle.dumps(value, protocol=5), [], [])
    fast = _serialize_buffer_fast(value)
    if fast is not None:
        return fast
    buffers: List = []
    contained: List = []
    sink = io.BytesIO()
    _RefAwarePickler(sink, buffers, contained).dump(value)
    return SerializedObject(sink.getvalue(), buffers, contained)


def serialize_exception(exc: BaseException) -> SerializedObject:
    from ray_tpu.core.exceptions import TaskError

    if not isinstance(exc, TaskError):
        exc = TaskError.from_exception(exc)
    try:
        out = serialize(exc)
    except Exception:
        out = serialize(TaskError(None, exc.remote_traceback, exc.task_desc))
    out.meta += META_EXCEPTION  # flag so get() raises instead of returning
    return out


def deserialize(data, out_of_band_owner: Any = None) -> Tuple[Any, bool]:
    """Deserialize wire bytes; returns ``(value, is_exception)``.

    ``data`` may be any buffer (bytes or a memoryview over shared memory).
    Buffers inside the mapping are NOT copied; numpy arrays alias it.
    ``out_of_band_owner`` is attached to reconstructed ObjectRefs so
    borrow-tracking knows where the value came from.
    """
    view = memoryview(data).cast("B")
    if bytes(view[: len(_MAGIC)]) != _MAGIC:
        raise ValueError("corrupt serialized object (bad magic)")
    offset = len(_MAGIC)
    (meta_len,) = struct.unpack_from("<I", view, offset)
    offset += 4
    meta = view[offset : offset + meta_len]
    offset += meta_len
    (n_buffers,) = struct.unpack_from("<I", view, offset)
    offset += 4
    buffers: List[memoryview] = []
    for _ in range(n_buffers):
        (buf_len,) = struct.unpack_from("<Q", view, offset)
        offset = _pad(offset + 8)
        buffers.append(view[offset : offset + buf_len])
        offset += buf_len

    meta_bytes = bytes(meta)
    is_exception = meta_bytes.endswith(META_EXCEPTION)
    if is_exception:
        meta_bytes = meta_bytes[: -len(META_EXCEPTION)]

    value = _unpickle(meta_bytes, buffers)
    return value, is_exception


class _RefAwareUnpickler(pickle.Unpickler):
    """Module-scope twin of _RefAwarePickler (building the class per
    deserialize() call showed up as ~7 us/object on nop-task storms)."""

    def persistent_load(self, pid):  # noqa: N802 (pickle API name)
        from ray_tpu.core.object_ref import ObjectRef

        tag, ref_bytes, owner_addr = pid
        if tag != "rtpu_ref":
            raise pickle.UnpicklingError(f"unknown persistent id {tag!r}")
        return ObjectRef._restore(ref_bytes, owner_addr)


def _unpickle(meta: bytes, buffers: List[memoryview]) -> Any:
    return _RefAwareUnpickler(io.BytesIO(meta), buffers=buffers).load()
