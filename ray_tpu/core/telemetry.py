"""Runtime telemetry: hot-path metric producers, timeline spans, clock sync.

Parity: the reference's ``src/ray/stats/metric_defs.cc`` (the ``ray_*``
series every core component emits) plus the per-task profile events that
feed ``ray timeline``.  This module is the single home of the runtime's
``ray_tpu_*`` metric instances and of the per-process span buffer; the
flush loops in worker/raylet/GCS drain both toward the GCS every
``metrics_report_period_s``.

Design constraints:

- **Hot paths stay cheap.**  Every helper early-returns on one module
  flag when ``metrics_enabled`` is off.  Per-method tag keys are cached
  (one dict lookup instead of a merge+sort per call), and the two
  per-frame byte counters are plain ints folded into real Counters only
  at flush time (``presample``) — the io loop is single-threaded per
  process, so unlocked increments are safe.
- **Metrics must never hurt the runtime.**  All helpers swallow nothing:
  they do only dict/arithmetic work that cannot raise in practice; the
  flush loops that do I/O live with their owners and drop on failure.

Span records are wall-clock (``time.time()``) pairs corrected by this
process's offset against the GCS clock (measured by ``clock_sync``
round trips — see ``measure_clock_offset``), so cross-host spans line
up in one Perfetto track without per-consumer correction.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu.util import metrics as _m

# ---------------------------------------------------------------------------
# enable gate
# ---------------------------------------------------------------------------

_enabled: Optional[bool] = None


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        env = os.environ.get("RAY_TPU_METRICS_ENABLED")
        if env is not None:
            _enabled = env.lower() in ("1", "true", "yes")
        else:
            try:
                from ray_tpu.core.config import get_config
                _enabled = bool(getattr(get_config(), "metrics_enabled",
                                        True))
            except Exception:  # noqa: BLE001 — config unavailable: stay on
                _enabled = True
    return _enabled


def _reset_for_tests() -> None:
    global _enabled, _clock_offset_s, _bytes_sent, _bytes_received
    _enabled = None
    _clock_offset_s = 0.0
    _bytes_sent = 0
    _bytes_received = 0
    _spans.clear()


# ---------------------------------------------------------------------------
# metric instances (created lazily so importing this module costs nothing;
# held in module globals so the weakref registry keeps them alive)
# ---------------------------------------------------------------------------

_LAT_BOUNDS = [0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
               0.5, 1.0, 2.5, 5.0, 10.0, 30.0]
_OCC_BOUNDS = [1, 2, 4, 8, 16, 32]
_MBPS_BOUNDS = [1, 5, 25, 50, 100, 250, 500, 1000, 2500, 5000]

_metrics: Dict[str, _m.Metric] = {}
_metrics_lock = threading.Lock()


def _get_metric(name: str, factory) -> _m.Metric:
    # double-checked: helpers run on the io loop AND submitting threads;
    # a racing double-create would register a loser whose pending data
    # drains as a duplicate orphan
    m = _metrics.get(name)
    if m is None:
        with _metrics_lock:
            m = _metrics.get(name)
            if m is None:
                m = _metrics[name] = factory()
    return m


def _counter(name: str, desc: str, tag_keys: Tuple[str, ...] = ()
             ) -> _m.Counter:
    return _get_metric(
        name, lambda: _m.Counter(name, desc, tag_keys=tag_keys))


def _gauge(name: str, desc: str, tag_keys: Tuple[str, ...] = ()) -> _m.Gauge:
    return _get_metric(
        name, lambda: _m.Gauge(name, desc, tag_keys=tag_keys))


def _hist(name: str, desc: str, bounds, tag_keys: Tuple[str, ...] = ()
          ) -> _m.Histogram:
    h = _get_metric(
        name, lambda: _m.Histogram(name, desc, boundaries=bounds,
                                   tag_keys=tag_keys))
    return h


# per-method tag-key cache: method -> (("method", m),)
_method_keys: Dict[str, Tuple] = {}


def _mkey(method: str) -> Tuple:
    key = _method_keys.get(method)
    if key is None:
        key = _method_keys[method] = (("method", method),)
    return key


_EMPTY_KEY: Tuple = ()

# ---------------------------------------------------------------------------
# RPC plane (core/rpc.py)
# ---------------------------------------------------------------------------

#: plain-int per-frame byte accumulators (io-loop-thread confined; folded
#: into Counters by presample() so the per-frame cost is one integer add)
_bytes_sent = 0
_bytes_received = 0


def add_bytes_sent(n: int) -> None:
    global _bytes_sent
    _bytes_sent += n


def add_bytes_received(n: int) -> None:
    global _bytes_received
    _bytes_received += n


def rpc_call_observed(method: str, seconds: float) -> None:
    """Client-side wall latency of one RPC attempt."""
    if not enabled():
        return
    _hist("ray_tpu_rpc_client_latency_s",
          "client-side RPC latency per method (per attempt)",
          _LAT_BOUNDS, ("method",)).observe_key(_mkey(method), seconds)


def rpc_retry(method: str) -> None:
    if not enabled():
        return
    _counter("ray_tpu_rpc_retries_total",
             "RPC retry attempts (beyond the first try)",
             ("method",)).inc_key(_mkey(method))


def rpc_deadline_exceeded(method: str) -> None:
    if not enabled():
        return
    _counter("ray_tpu_rpc_deadline_exceeded_total",
             "retried RPC chains that ran out of deadline budget",
             ("method",)).inc_key(_mkey(method))


# ---------------------------------------------------------------------------
# transfer plane (core/raylet.py)
# ---------------------------------------------------------------------------

_PATH_KEYS = {"net": (("path", "net"),), "shm": (("path", "shm"),)}
_RESULT_KEYS = {("ok", "net"): (("path", "net"), ("result", "ok")),
                ("ok", "shm"): (("path", "shm"), ("result", "ok")),
                ("failed", "net"): (("path", "net"), ("result", "failed")),
                ("failed", "shm"): (("path", "shm"), ("result", "failed"))}


def transfer_chunk(path: str, nbytes: int) -> None:
    """One object-transfer chunk landed (path: net|shm)."""
    if not enabled():
        return
    key = _PATH_KEYS[path]
    _counter("ray_tpu_transfer_chunks_total",
             "object-transfer chunks received", ("path",)).inc_key(key)
    _counter("ray_tpu_transfer_bytes_total",
             "object-transfer bytes received", ("path",)).inc_key(
        key, float(nbytes))


def transfer_window_occupancy(depth: int) -> None:
    """In-flight chunk requests at the moment a new one is issued."""
    if not enabled():
        return
    _hist("ray_tpu_transfer_window_occupancy",
          "in-flight chunk requests per pull when issuing the next",
          _OCC_BOUNDS).observe_key(_EMPTY_KEY, depth)


def transfer_failover() -> None:
    if not enabled():
        return
    _counter("ray_tpu_transfer_failovers_total",
             "mid-transfer source failovers (chunks re-queued to "
             "surviving sources)").inc_key(_EMPTY_KEY)


def transfer_pull_done(ok: bool, path: str, nbytes: int,
                       elapsed_s: float, n_sources: int) -> None:
    if not enabled():
        return
    _counter("ray_tpu_transfer_pulls_total",
             "object pulls completed, by result and data path",
             ("path", "result")).inc_key(
        _RESULT_KEYS[("ok" if ok else "failed", path)])
    if ok and elapsed_s > 0:
        _hist("ray_tpu_transfer_throughput_mbps",
              "per-pull transfer throughput (MB/s)",
              _MBPS_BOUNDS).observe_key(
            _EMPTY_KEY, nbytes / elapsed_s / 1e6)


# ---------------------------------------------------------------------------
# object-store spill tier
# ---------------------------------------------------------------------------

def store_spilled(nbytes: int) -> None:
    """One cold primary written to the spill tier."""
    if not enabled():
        return
    _counter("ray_tpu_store_spilled_bytes_total",
             "bytes spilled from the arena to the disk/URI tier"
             ).inc_key(_EMPTY_KEY, float(nbytes))


def store_restored(nbytes: int) -> None:
    """One spilled blob transparently restored into the arena."""
    if not enabled():
        return
    _counter("ray_tpu_store_restored_bytes_total",
             "bytes restored from the spill tier into the arena"
             ).inc_key(_EMPTY_KEY, float(nbytes))


# ---------------------------------------------------------------------------
# scheduler / lease plane
# ---------------------------------------------------------------------------

def lease_granted(wait_s: float) -> None:
    """Queue-entry -> grant latency of one worker lease on the raylet."""
    if not enabled():
        return
    _hist("ray_tpu_lease_grant_latency_s",
          "worker-lease queue wait until grant on the raylet",
          _LAT_BOUNDS).observe_key(_EMPTY_KEY, wait_s)


def task_dispatch_latency(seconds: float) -> None:
    """Owner-side submit -> push-to-worker latency of one task."""
    if not enabled():
        return
    _hist("ray_tpu_task_dispatch_latency_s",
          "owner-side task submit -> dispatch-to-worker latency",
          _LAT_BOUNDS).observe_key(_EMPTY_KEY, seconds)


_BATCH_BOUNDS = [1, 2, 4, 8, 16, 32, 64, 128, 256]


def sched_registration_batch(n: int) -> None:
    """One coalesced actor/PG registration batch landed at the GCS;
    ``n`` is the actors it carried (1 = no coalescing happened)."""
    if not enabled():
        return
    _hist("ray_tpu_sched_registration_batch_size",
          "actors per coalesced register_actor_batch RPC at the GCS",
          _BATCH_BOUNDS).observe_key(_EMPTY_KEY, n)


_POOL_KEYS = {True: (("result", "hit"),), False: (("result", "miss"),)}


def sched_warm_pool(hit: bool, n: int = 1) -> None:
    """Raylet-side: a lease was served from the warm idle pool (hit) or
    had to wait for a fresh worker spawn (miss)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_sched_warm_pool_total",
             "worker leases served from the warm pool (hit) vs waiting "
             "on a spawn (miss)", ("result",)).inc_key(
        _POOL_KEYS[hit], float(n))


def sched_lease_cache(hit: bool, n: int = 1) -> None:
    """Owner-side: a task claimed a cached compatible lease (hit) or
    fell through to a raylet lease round trip (miss)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_sched_lease_cache_total",
             "owner-side lease-cache claims (hit) vs raylet lease "
             "round trips (miss)", ("result",)).inc_key(
        _POOL_KEYS[hit], float(n))


# ---------------------------------------------------------------------------
# GCS plane
# ---------------------------------------------------------------------------

_channel_keys: Dict[str, Tuple] = {}


def gcs_published(channel: str, n_subscribers: int) -> None:
    """One pubsub publish; ``channel`` is folded to its prefix (the part
    before ``:``) so per-actor channels don't explode cardinality."""
    if not enabled():
        return
    prefix = channel.split(":", 1)[0]
    key = _channel_keys.get(prefix)
    if key is None:
        key = _channel_keys[prefix] = (("channel", prefix),)
    _counter("ray_tpu_gcs_publish_total",
             "GCS pubsub publishes by channel prefix",
             ("channel",)).inc_key(key)
    if n_subscribers:
        _counter("ray_tpu_gcs_publish_deliveries_total",
                 "GCS pubsub per-subscriber deliveries by channel prefix",
                 ("channel",)).inc_key(key, float(n_subscribers))


def heartbeat_miss() -> None:
    """Raylet-side: one failed/timed-out health report to the GCS."""
    if not enabled():
        return
    _counter("ray_tpu_gcs_heartbeat_misses_total",
             "raylet health reports that failed or timed out"
             ).inc_key(_EMPTY_KEY)


def node_death() -> None:
    if not enabled():
        return
    _counter("ray_tpu_gcs_node_deaths_total",
             "nodes the GCS declared dead").inc_key(_EMPTY_KEY)


def autoscaler_decision(action: str) -> None:
    """One AutoscalerMonitor policy verdict (scale_up | allow_down |
    hold), counted per tick."""
    if not enabled():
        return
    _counter("ray_tpu_autoscaler_decisions_total",
             "scaling-policy decisions emitted by the autoscaler "
             "monitor", ("action",)).inc_key((("action", action),))


def autoscaler_launch_failure() -> None:
    """A provider node launch failed (or the launch_fail failpoint
    fired); the monitor backs off exponentially and retries."""
    if not enabled():
        return
    _counter("ray_tpu_autoscaler_launch_failures_total",
             "node provider launches that failed (retried with "
             "backoff)").inc_key(_EMPTY_KEY)


def autoscaler_target_nodes(n: int) -> None:
    if not enabled():
        return
    _gauge("ray_tpu_autoscaler_target_nodes",
           "worker nodes the autoscaler currently maintains "
           "(provider view)").set_key(_EMPTY_KEY, float(n))


def node_drain_transition(state: str) -> None:
    """One node lifecycle transition (docs/autoscaler.md drain
    protocol): DRAINING (drain started), DRAINED (migration complete),
    ACTIVE (drain aborted, node returned to service)."""
    if not enabled():
        return
    _counter("ray_tpu_gcs_node_drain_transitions_total",
             "node lifecycle transitions driven by the drain protocol",
             ("state",)).inc_key((("state", state),))


def task_events_dropped(job_id: Optional[str], n: int) -> None:
    if not enabled() or n <= 0:
        return
    job = job_id or "unknown"
    _counter("ray_tpu_task_events_dropped_total",
             "task events evicted from the GCS ring buffer before "
             "any consumer read them", ("job",)).inc_key(
        (("job", job),), float(n))


# ---------------------------------------------------------------------------
# per-job attribution (tenancy accounting — docs/observability.md):
# counters tagged by job hex so consumption rolls up per tenant in the
# GCS table and `ray-tpu top --jobs`.  Jobs are few (the tagset cap
# guards runaways), and every helper is one cached-key counter inc.
# ---------------------------------------------------------------------------

_job_keys: Dict[str, Tuple] = {}


def _jobkey(job: Optional[str]) -> Tuple:
    job = job or "unknown"
    key = _job_keys.get(job)
    if key is None:
        key = _job_keys[job] = (("job", job),)
    return key


def job_task_finished(job: Optional[str], exec_seconds: float) -> None:
    """Executor-side: one task body finished; ``exec_seconds`` is body
    wall time (arg fetch and env setup excluded — same split the
    analyzer's exec phase uses)."""
    if not enabled():
        return
    key = _jobkey(job)
    _counter("ray_tpu_job_tasks_total",
             "task bodies executed, by owning job",
             ("job",)).inc_key(key)
    if exec_seconds > 0:
        _counter("ray_tpu_job_cpu_seconds_total",
                 "task-body execution seconds, by owning job",
                 ("job",)).inc_key(key, float(exec_seconds))


def job_submitted_bytes(job: Optional[str], nbytes: int) -> None:
    """Owner-side: bytes serialized into the object plane by put()."""
    if not enabled() or nbytes <= 0:
        return
    _counter("ray_tpu_job_submitted_bytes_total",
             "bytes put() into the object plane, by owning job",
             ("job",)).inc_key(_jobkey(job), float(nbytes))


def job_spilled_bytes(job: Optional[str], nbytes: int) -> None:
    """Raylet-side: one primary spilled; the job is derived from the
    ObjectID's embedded lineage (ObjectID -> TaskID -> JobID)."""
    if not enabled() or nbytes <= 0:
        return
    _counter("ray_tpu_job_spilled_bytes_total",
             "bytes spilled to the disk/URI tier, by owning job",
             ("job",)).inc_key(_jobkey(job), float(nbytes))


# ---------------------------------------------------------------------------
# metrics history + alerting plane (core/metrics_history.py; GCS-side)
# ---------------------------------------------------------------------------

def history_stats(points: int, series: int, evicted_delta: int) -> None:
    """Ring accounting exported each sample tick: resident points,
    live series, and evictions since the last tick (the memory-bound
    proof: points <= series x window/interval, overflow is counted)."""
    if not enabled():
        return
    _gauge("ray_tpu_metrics_history_points",
           "time-series points resident in the GCS history rings"
           ).set_key(_EMPTY_KEY, float(points))
    _gauge("ray_tpu_metrics_history_series",
           "series (incl. derived signals) with a live history ring"
           ).set_key(_EMPTY_KEY, float(series))
    if evicted_delta > 0:
        _counter("ray_tpu_metrics_history_evicted_total",
                 "history points evicted by the per-series ring cap "
                 "(window_s / interval_s points per series)"
                 ).inc_key(_EMPTY_KEY, float(evicted_delta))


def history_sample_failure() -> None:
    """One sample tick skipped (failpoint / ingest error): the ring
    misses a point but the evaluator keeps running."""
    if not enabled():
        return
    _counter("ray_tpu_metrics_history_sample_failures_total",
             "history sample ticks that failed and were skipped "
             "(the alert evaluator keeps running)"
             ).inc_key(_EMPTY_KEY)


def alerts_stats(firing: int, transitions: int) -> None:
    if not enabled():
        return
    _gauge("ray_tpu_alerts_firing",
           "alert rule instances currently in state firing"
           ).set_key(_EMPTY_KEY, float(firing))
    if transitions > 0:
        _counter("ray_tpu_alerts_transitions_total",
                 "alert state transitions (pending->firing, "
                 "firing->resolved, restored re-fires)"
                 ).inc_key(_EMPTY_KEY, float(transitions))


# ---------------------------------------------------------------------------
# GCS persistence / HA plane (core/wal.py + table_storage.py)
# ---------------------------------------------------------------------------

def gcs_persist_failure(backend: str) -> None:
    """One failed ``TableStorage.store()`` — the snapshot that should
    have landed didn't; the WAL (if healthy) still covers the acked
    mutations, but the compaction base is stale."""
    if not enabled():
        return
    _counter("ray_tpu_gcs_persist_failures_total",
             "GCS table snapshot writes that failed (by backend)",
             ("backend",)).inc_key((("backend", backend),))


def gcs_wal_append(n: int = 1) -> None:
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_gcs_wal_appends_total",
             "typed mutation records appended to the GCS write-ahead "
             "log").inc_key(_EMPTY_KEY, float(n))


def gcs_wal_fsync(n: int = 1) -> None:
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_gcs_wal_fsyncs_total",
             "group-commit fsync rounds of the GCS write-ahead log "
             "(many acked mutations share one round)"
             ).inc_key(_EMPTY_KEY, float(n))


def gcs_wal_append_failure(n: int = 1) -> None:
    """A WAL append/flush failed: the GCS degraded to snapshot-only
    persistence (tight debounce) rather than failing the mutation."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_gcs_wal_append_failures_total",
             "failed WAL appends/flushes (the GCS degrades to "
             "snapshot-only persistence)").inc_key(_EMPTY_KEY, float(n))


def gcs_wal_replayed(n: int) -> None:
    """Records replayed from the WAL at GCS startup (restart recovery)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_gcs_wal_replayed_records_total",
             "WAL records replayed on top of the snapshot at GCS "
             "startup").inc_key(_EMPTY_KEY, float(n))


def gcs_wal_size(nbytes: int) -> None:
    if not enabled():
        return
    _gauge("ray_tpu_gcs_wal_size_bytes",
           "current byte size of the GCS write-ahead log (drops to the "
           "header size at each compaction)").set_key(
        _EMPTY_KEY, float(nbytes))


def gcs_recovery_duration(seconds: float) -> None:
    """Head-restart recovery duration: snapshot load + WAL replay +
    restored-actor revalidation, measured once per recovery."""
    if not enabled():
        return
    _gauge("ray_tpu_gcs_recovery_duration_s",
           "duration of the last GCS restart recovery (snapshot load + "
           "WAL replay + restored-actor revalidation)").set_key(
        _EMPTY_KEY, float(seconds))


# ---------------------------------------------------------------------------
# profiling plane (core/profiler.py / GCS profile ring)
# ---------------------------------------------------------------------------

def profiler_samples(n: int) -> None:
    """Stack samples folded this window (called once per drain, never
    per sample — the sampler keeps plain ints)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_profiler_samples_total",
             "profiler stack samples taken").inc_key(_EMPTY_KEY, float(n))


def profiler_stack_drops(n: int) -> None:
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_profiler_stacks_dropped_total",
             "profiler samples dropped by the per-process "
             "profiler_max_stacks fold-table cap").inc_key(
        _EMPTY_KEY, float(n))


def profiler_records_evicted(n: int) -> None:
    """GCS-side: profile records the ring evicted before any consumer
    read them."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_profiler_records_evicted_total",
             "profile records evicted from the GCS ring buffer "
             "(raise profiler_table_size to keep more)").inc_key(
        _EMPTY_KEY, float(n))


# ---------------------------------------------------------------------------
# serving plane (serve/_internal.py, serve/batching.py, serve/http_proxy.py)
# ---------------------------------------------------------------------------

_dep_keys: Dict[str, Tuple] = {}


def _dkey(deployment: str) -> Tuple:
    key = _dep_keys.get(deployment)
    if key is None:
        key = _dep_keys[deployment] = (("deployment", deployment),)
    return key


_OCC_FRAC_BOUNDS = [0.125, 0.25, 0.375, 0.5, 0.625, 0.75, 0.875, 1.0]
_SHED_KEYS: Dict[Tuple[str, str], Tuple] = {}


def serve_request_observed(deployment: str, seconds: float,
                           trace_id: Optional[str] = None) -> None:
    """End-to-end latency of one served request (replica-side: queue
    wait + decode; proxy-side spans add transport on top).  When the
    request was traced, the observation carries an OpenMetrics exemplar
    linking its latency bucket to the concrete ``trace_id`` — a
    dashboard can jump from "p99 spiked" straight to ``ray-tpu trace``."""
    if not enabled():
        return
    _hist("ray_tpu_serve_request_latency_s",
          "serve request latency (admission to completion) per deployment",
          _LAT_BOUNDS, ("deployment",)).observe_key(
        _dkey(deployment), seconds,
        exemplar={"trace_id": trace_id} if trace_id else None)


def serve_ttft_observed(deployment: str, seconds: float) -> None:
    """Time-to-first-token of one STREAMING (?stream=1) request: submit
    to first generated token, the latency a streaming client actually
    perceives."""
    if not enabled():
        return
    _hist("ray_tpu_serve_ttft_seconds",
          "time-to-first-token for streaming serve requests",
          _LAT_BOUNDS, ("deployment",)).observe_key(
        _dkey(deployment), seconds)


_STEP_BOUNDS = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                0.25, 0.5, 1.0]


def serve_decode_step(deployment: str, seconds: float) -> None:
    """Wall duration of one continuous-batching decode step (the jitted
    hot path; regressions here multiply into every token)."""
    if not enabled():
        return
    _hist("ray_tpu_serve_decode_step_seconds",
          "per-decode-step latency of the continuous batcher",
          _STEP_BOUNDS, ("deployment",)).observe_key(
        _dkey(deployment), seconds)


def serve_request_shed(deployment: str, where: str) -> None:
    """One request shed by backpressure (``where``: proxy|replica)."""
    if not enabled():
        return
    key = _SHED_KEYS.get((deployment, where))
    if key is None:
        key = _SHED_KEYS[(deployment, where)] = (
            ("deployment", deployment), ("where", where))
    _counter("ray_tpu_serve_shed_total",
             "serve requests shed by backpressure (429), by layer",
             ("deployment", "where")).inc_key(key)


def serve_batch_occupancy(deployment: str, frac: float) -> None:
    """Slot-pool occupancy of one continuous-batching decode step."""
    if not enabled():
        return
    _hist("ray_tpu_serve_batch_occupancy",
          "continuous-batch slot occupancy per decode step (fraction)",
          _OCC_FRAC_BOUNDS, ("deployment",)).observe_key(
        _dkey(deployment), frac)


def serve_queue_depth(deployment: str, depth: int) -> None:
    """Pending (unadmitted) requests across a deployment's replicas —
    the autoscaler's primary signal, refreshed each reconcile tick."""
    if not enabled():
        return
    _gauge("ray_tpu_serve_queue_depth",
           "queued serve requests awaiting a batch slot, per deployment",
           ("deployment",)).set_key(_dkey(deployment), float(depth))


def serve_replicas(deployment: str, n: int) -> None:
    if not enabled():
        return
    _gauge("ray_tpu_serve_replicas",
           "live replicas per serve deployment",
           ("deployment",)).set_key(_dkey(deployment), float(n))


# -- sharded serving (serve/sharded.py, serve/kv_cache.py) ------------------

def serve_kv_pages(deployment: str, active: int, allocated_total: int,
                   freed_total: int) -> None:
    """Paged-KV accounting for one deployment, aggregated across its
    replicas each controller reconcile tick.  ``active`` pages are
    pinned arena objects; allocated == freed once a deployment drains
    (the chaos suite's no-leak invariant)."""
    if not enabled():
        return
    key = _dkey(deployment)
    _gauge("ray_tpu_serve_kv_pages_active",
           "live (pinned) KV cache pages in the object-store arena, "
           "per deployment", ("deployment",)).set_key(key, float(active))
    _gauge("ray_tpu_serve_kv_pages_allocated_total",
           "KV cache pages allocated since deployment start",
           ("deployment",)).set_key(key, float(allocated_total))
    _gauge("ray_tpu_serve_kv_pages_freed_total",
           "KV cache pages freed since deployment start",
           ("deployment",)).set_key(key, float(freed_total))


def serve_kv_occupancy(deployment: str, frac: float) -> None:
    """Fraction of the replica page budget (kv_max_pages) in use —
    the continuous batcher's admission signal for paged KV."""
    if not enabled():
        return
    _gauge("ray_tpu_serve_kv_page_occupancy",
           "fraction of the per-replica KV page budget in use",
           ("deployment",)).set_key(_dkey(deployment), float(frac))


def serve_gang_bringup(deployment: str, seconds: float, shards: int) -> None:
    """Wall time from first gang-member creation to all-shards-ready
    for one sharded replica (rides the batched registration +
    pipelined bring-up plane; regressions here multiply into every
    gang respawn after a shard death)."""
    if not enabled():
        return
    _hist("ray_tpu_serve_gang_bringup_seconds",
          "sharded-replica gang bring-up latency (create -> all ready)",
          _LAT_BOUNDS, ("deployment",)).observe_key(
        _dkey(deployment), seconds)
    _gauge("ray_tpu_serve_gang_shards",
           "shards per gang replica of the deployment",
           ("deployment",)).set_key(_dkey(deployment), float(shards))


def serve_gang_death(deployment: str) -> None:
    """One gang torn down because a shard died (all-or-nothing
    readiness: the controller respawns the whole gang)."""
    if not enabled():
        return
    _counter("ray_tpu_serve_gang_deaths_total",
             "sharded-replica gangs killed by a shard death",
             ("deployment",)).inc_key(_dkey(deployment))


# -- serving economics (prefix cache / multiplexing / cross-gang) -----------

_PREFIX_KEYS: Dict[Tuple[str, str], Tuple] = {}

#: swap = engine build + weight restore by arena ref; sub-ms for toys,
#: seconds for real checkpoints — bounds span both
_SWAP_BOUNDS = [0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                5.0, 10.0]


def serve_prefix_cache(deployment: str, result: str) -> None:
    """One prefix-cache lookup at request admission (``result``:
    hit|partial|miss).  The hit ratio is the headline serving-economics
    number — every hit token is prefill compute NOT spent."""
    if not enabled():
        return
    key = _PREFIX_KEYS.get((deployment, result))
    if key is None:
        key = _PREFIX_KEYS[(deployment, result)] = (
            ("deployment", deployment), ("result", result))
    _counter("ray_tpu_serve_prefix_cache_total",
             "KV prefix-cache lookups by outcome (hit|partial|miss)",
             ("deployment", "result")).inc_key(key)


def serve_prefix_pages_shared(deployment: str, n: int) -> None:
    """Sealed KV pages currently held by the prefix cache across the
    deployment's replicas (each possibly adopted by many requests —
    the sharing that converts HBM into throughput)."""
    if not enabled():
        return
    _gauge("ray_tpu_serve_prefix_pages_shared",
           "KV pages resident in the prefix cache, per deployment",
           ("deployment",)).set_key(_dkey(deployment), float(n))


def serve_mux_swap(deployment: str, seconds: float) -> None:
    """One model weight swap on a multiplexed replica (cache miss in
    the resident set).  The histogram prices misses; the router's
    model-resident steering keeps the rate low in steady state."""
    if not enabled():
        return
    _counter("ray_tpu_serve_mux_swaps_total",
             "model weight swaps on multiplexed replicas",
             ("deployment",)).inc_key(_dkey(deployment))
    _hist("ray_tpu_serve_mux_swap_seconds",
          "latency of one multiplexed model swap (build + load by ref)",
          _SWAP_BOUNDS, ("deployment",)).observe_key(
        _dkey(deployment), seconds)


def serve_xgang_steered(deployment: str) -> None:
    """One request steered by next-step-boundary slot availability —
    the router narrowed its candidate set to replicas with a free batch
    slot (cross-gang continuous batching in effect)."""
    if not enabled():
        return
    _counter("ray_tpu_serve_xgang_steered_total",
             "requests steered to a gang with a free batch slot",
             ("deployment",)).inc_key(_dkey(deployment))


def gcs_respawn() -> None:
    """The head supervisor respawned a died GCS/head process."""
    if not enabled():
        return
    _counter("ray_tpu_gcs_respawns_total",
             "automatic head (GCS) respawns by the driver-side "
             "supervisor").inc_key(_EMPTY_KEY)


# ---------------------------------------------------------------------------
# RL pipeline (rllib decoupled acting/learning — docs/rl_pipeline.md)
# ---------------------------------------------------------------------------

def rl_inference_batch(occupancy: float) -> None:
    """One centralized-inference dispatch: ``occupancy`` is real rows /
    padded bucket rows (1.0 = no padding waste); the dispatch count is
    the histogram's sample count."""
    if not enabled():
        return
    _hist("ray_tpu_rl_inference_batch_occupancy",
          "rows / padded bucket per centralized RL inference dispatch",
          _OCC_FRAC_BOUNDS).observe_key(_EMPTY_KEY, occupancy)


def rl_fragment_queue_depth(depth: int) -> None:
    """Learner-side: trajectory fragments ready (returned by env actors)
    but not yet consumed by the PPO update — sustained growth means the
    learner is the bottleneck, sustained zero means acting is."""
    if not enabled():
        return
    _gauge("ray_tpu_rl_fragment_queue_depth",
           "ready-but-unconsumed trajectory fragments at the RL learner"
           ).set_key(_EMPTY_KEY, float(depth))


def rl_weight_sync_age(age_s: float) -> None:
    """Inference-actor-side: seconds since the last weight publish when
    a batch is dispatched — the acting policy's staleness in wall time."""
    if not enabled():
        return
    _gauge("ray_tpu_rl_weight_sync_age_s",
           "age of the acting policy's weights at inference dispatch"
           ).set_key(_EMPTY_KEY, age_s)


def rl_fragments_dropped_stale(n: int = 1) -> None:
    """Fragments discarded by the learner because their weights version
    lagged more than ``rl_max_fragment_lag`` behind."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_rl_fragments_dropped_stale_total",
             "trajectory fragments dropped by the off-policy "
             "staleness bound").inc_key(_EMPTY_KEY, float(n))


# ---------------------------------------------------------------------------
# device plane (core/device_telemetry.py — XLA compiles, step phases,
# MFU/goodput, gang rank skew; docs/observability.md "device plane")
# ---------------------------------------------------------------------------

#: compile cost spans four orders of magnitude: a toy-decoder bucket
#: retrace is ~10 ms on CPU, a pod-scale train step graph is minutes
_COMPILE_BOUNDS = [0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                   15.0, 60.0]
_compile_keys: Dict[Tuple[str, str], Tuple] = {}
_fn_keys: Dict[str, Tuple] = {}
_phase_keys: Dict[Tuple[str, str], Tuple] = {}
_plane_keys: Dict[str, Tuple] = {}


def _fnkey(fn: str) -> Tuple:
    key = _fn_keys.get(fn)
    if key is None:
        key = _fn_keys[fn] = (("fn", fn),)
    return key


def xla_compile(fn: str, reason: str, seconds: float) -> None:
    """One detected XLA compilation of a jitted step entry point
    (``reason``: first | shape_miss).  Steady-state steps must never
    land here — the RecompileStorm alert rides the rate of this
    counter."""
    if not enabled():
        return
    key = _compile_keys.get((fn, reason))
    if key is None:
        key = _compile_keys[(fn, reason)] = (("fn", fn),
                                             ("reason", reason))
    _counter("ray_tpu_xla_compiles_total",
             "XLA compilations detected at instrumented step entry "
             "points, by function and trigger (first | shape_miss)",
             ("fn", "reason")).inc_key(key)
    _hist("ray_tpu_xla_compile_seconds",
          "wall seconds of one detected compilation (traced call incl. "
          "first execution)", _COMPILE_BOUNDS,
          ("fn",)).observe_key(_fnkey(fn), seconds)


def step_phase(plane: str, phase: str, seconds: float) -> None:
    """One step's time in one phase of the device-step ladder
    (``data_wait`` / ``host`` / ``device`` / ``sync``); the four
    observations of a step sum to its wall time."""
    if not enabled():
        return
    key = _phase_keys.get((plane, phase))
    if key is None:
        key = _phase_keys[(plane, phase)] = (("plane", plane),
                                             ("phase", phase))
    _hist("ray_tpu_step_phase_seconds",
          "per-step wall time split over the data_wait/host/device/sync "
          "phase ladder, by workload plane",
          _STEP_BOUNDS, ("plane", "phase")).observe_key(key, seconds)


def _planekey(plane: str) -> Tuple:
    key = _plane_keys.get(plane)
    if key is None:
        key = _plane_keys[plane] = (("plane", plane),)
    return key


def step_goodput(plane: str, per_s: float) -> None:
    """Rolling goodput of the instrumented step loop: tokens/s for
    train+serve, rows/s for RL inference — the numerator of MFU."""
    if not enabled():
        return
    _gauge("ray_tpu_step_goodput_per_s",
           "tokens-or-requests per second through the instrumented "
           "step loop, by workload plane",
           ("plane",)).set_key(_planekey(plane), per_s)


def train_step_quality(mfu: float, data_wait_frac: float) -> None:
    """Train-plane step efficiency: model FLOPs utilization and the
    fraction of step wall time spent waiting on input data (the
    starved-accelerator signal the autoscaler and `ray-tpu top` read
    via the train:mfu / train:step_data_wait_frac recording rules)."""
    if not enabled():
        return
    _gauge("ray_tpu_train_mfu",
           "rolling model-FLOPs utilization of the train step loop"
           ).set_key(_EMPTY_KEY, mfu)
    _gauge("ray_tpu_train_step_data_wait_frac",
           "fraction of train step wall time spent waiting for input "
           "data (prefetch handoff)").set_key(_EMPTY_KEY, data_wait_frac)


def serve_decode_device_frac(deployment: str, frac: float) -> None:
    """Fraction of decode-step wall time the device was computing
    (vs host dispatch/sync): low values mean the chip is starved by
    host-side batching work."""
    if not enabled():
        return
    _gauge("ray_tpu_serve_decode_device_frac",
           "device-compute fraction of decode-step wall time per "
           "deployment", ("deployment",)).set_key(_dkey(deployment), frac)


_skew_keys: Dict[Tuple[str, str], Tuple] = {}


def gang_rank_skew(deployment: str, skew_s: float, straggler: int) -> None:
    """Gang-level rank skew: max minus min mean per-rank step duration
    over the rolling step window, tagged with the slowest rank so the
    GangStraggler alert names it."""
    if not enabled():
        return
    tag = (deployment, str(int(straggler)))
    key = _skew_keys.get(tag)
    if key is None:
        key = _skew_keys[tag] = (("deployment", deployment),
                                 ("straggler", tag[1]))
    _gauge("ray_tpu_gang_rank_skew_seconds",
           "spread (max-min) of mean per-rank step duration over a "
           "gang's step window, tagged with the straggling rank",
           ("deployment", "straggler")).set_key(key, skew_s)


# ---------------------------------------------------------------------------
# streaming data plane (data/streaming.py — docs/data.md)
# ---------------------------------------------------------------------------

_REASON_KEYS = {"consumer": (("reason", "consumer"),),
                "arena": (("reason", "arena"),)}
_HIT_KEYS = {True: (("result", "hit"),), False: (("result", "miss"),)}


def data_blocks_in_flight(depth: int) -> None:
    """Streaming executor window occupancy: blocks executing or
    produced-but-unconsumed, sampled at every admission round."""
    if not enabled():
        return
    _gauge("ray_tpu_data_blocks_in_flight",
           "streaming-dataset blocks in flight (executing + ready, "
           "bounded by streaming_block_budget)").set_key(
        _EMPTY_KEY, float(depth))


def data_backpressure_stall(reason: str, n: int = 1) -> None:
    """One producer-side admission stall (``reason``: consumer lag or
    local arena pressure above streaming_arena_watermark)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_data_backpressure_stalls_total",
             "streaming-ingest admission stalls, by backpressure signal",
             ("reason",)).inc_key(_REASON_KEYS[reason], float(n))


def data_blocks_produced(n: int = 1) -> None:
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_data_blocks_produced_total",
             "blocks produced by streaming dataset execution"
             ).inc_key(_EMPTY_KEY, float(n))


def data_prefetch(hit: bool, n: int = 1) -> None:
    """Shard-iterator prefetch accounting: the consumer asked for the
    next batch and it was already assembled (hit) or it had to wait
    (miss) — hit/(hit+miss) is the prefetch hit ratio."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_data_prefetch_total",
             "streaming-shard batch requests served from the prefetch "
             "queue (hit) vs waiting on assembly (miss)",
             ("result",)).inc_key(_HIT_KEYS[hit], float(n))


def data_shuffle_spilled(nbytes: int) -> None:
    """Arena bytes the local spill tier absorbed during one streaming
    shuffle (its intermediate working set beyond the arena)."""
    if not enabled() or nbytes <= 0:
        return
    _counter("ray_tpu_data_shuffle_spilled_bytes_total",
             "bytes spilled to the disk tier by streaming-shuffle "
             "intermediates").inc_key(_EMPTY_KEY, float(nbytes))


def sched_locality_lease(n: int = 1) -> None:
    """Owner-side: one worker-lease request routed to a remote raylet
    because the head task's plasma args live there (task locality)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_sched_locality_leases_total",
             "lease requests routed to the raylet holding the task's "
             "plasma args (owner-side locality)").inc_key(
        _EMPTY_KEY, float(n))


# ---------------------------------------------------------------------------
# distributed tracing plane (core/tracing.py / GCS trace ring)
# ---------------------------------------------------------------------------

def trace_spans_ingested(n: int) -> None:
    """GCS-side: trace spans accepted into the assembly ring."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_trace_spans_total",
             "trace spans ingested by the GCS trace ring"
             ).inc_key(_EMPTY_KEY, float(n))


def trace_retained(n: int = 1) -> None:
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_trace_retained_total",
             "traces kept by tail sampling (errors/sheds/SLO misses "
             "always; fast successes at trace_sample_keep_fraction)"
             ).inc_key(_EMPTY_KEY, float(n))


def trace_sampled_out(n: int = 1) -> None:
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_trace_sampled_out_total",
             "completed traces dropped by tail sampling (fast successes "
             "beyond the keep fraction)").inc_key(_EMPTY_KEY, float(n))


def trace_evicted(n: int = 1) -> None:
    """GCS-side: traces evicted from the ring before any consumer read
    them (raise trace_table_size to keep more)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_trace_evicted_total",
             "traces evicted from the GCS trace ring"
             ).inc_key(_EMPTY_KEY, float(n))


# ---------------------------------------------------------------------------
# incident forensics (core/flight_recorder.py + GCS incident journal)
# ---------------------------------------------------------------------------

def events_evicted(n: int = 1) -> None:
    """GCS-side: cluster-event records displaced from a per-severity
    retention ring (raise event_ring_size to keep more)."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_events_evicted_total",
             "cluster-event records evicted from the per-severity "
             "retention rings").inc_key(_EMPTY_KEY, float(n))


def incident_opened(kind: str) -> None:
    """GCS-side: an incident auto-opened (kind: death | alert)."""
    if not enabled():
        return
    _counter("ray_tpu_incidents_total",
             "incidents auto-opened by the GCS journal",
             ("kind",)).inc_key((("kind", kind),), 1.0)


def incidents_open(n: int) -> None:
    """GCS-side gauge: incidents currently retained in the journal."""
    if not enabled():
        return
    _gauge("ray_tpu_incidents_open",
           "incidents retained in the GCS journal"
           ).set_key(_EMPTY_KEY, float(n))


def flight_tail_shipped(n: int = 1) -> None:
    """GCS-side: dead-process flight tails attached to incidents."""
    if not enabled() or n <= 0:
        return
    _counter("ray_tpu_flight_tails_shipped_total",
             "dead-process flight-recorder tails shipped to the GCS "
             "incident journal").inc_key(_EMPTY_KEY, float(n))


def flight_frames(n: int) -> None:
    """Per-process gauge, set from the flush loops (never per-frame):
    frames this process has recorded into its flight ring."""
    if not enabled():
        return
    _gauge("ray_tpu_flight_frames_total",
           "frames recorded into this process's flight-recorder ring"
           ).set_key(_EMPTY_KEY, float(n))


# ---------------------------------------------------------------------------
# gauges set by the flush loops (samplers run right before a flush)
# ---------------------------------------------------------------------------

def set_gauge(name: str, desc: str, value: float,
              tags: Optional[Dict[str, str]] = None) -> None:
    if not enabled():
        return
    keys = tuple(sorted(tags)) if tags else ()
    _gauge(name, desc, keys).set_key(
        tuple(sorted(tags.items())) if tags else _EMPTY_KEY, value)


def presample() -> None:
    """Fold the plain-int hot counters into real Counter objects; called
    by each flush loop right before ``metrics.flush_all()``."""
    global _bytes_sent, _bytes_received
    if not enabled():
        return
    sent, _bytes_sent = _bytes_sent, 0
    recv, _bytes_received = _bytes_received, 0
    if sent:
        _counter("ray_tpu_rpc_bytes_sent_total",
                 "bytes written to RPC transports (frames incl. OOB "
                 "payloads)").inc_key(_EMPTY_KEY, float(sent))
    if recv:
        _counter("ray_tpu_rpc_bytes_received_total",
                 "bytes received from RPC transports"
                 ).inc_key(_EMPTY_KEY, float(recv))


# ---------------------------------------------------------------------------
# timeline spans (chrome-trace complete events, GCS-clock aligned)
# ---------------------------------------------------------------------------

def _span_cap() -> int:
    try:
        from ray_tpu.core.config import get_config
        return int(getattr(get_config(), "telemetry_spans_buffer_size",
                           4096))
    except Exception:  # noqa: BLE001
        return 4096


_spans: "deque[Dict[str, Any]]" = deque(maxlen=4096)
_span_cap_applied = False
_clock_offset_s = 0.0


def spans_enabled() -> bool:
    return enabled()


def record_span(cat: str, name: str, start: float, end: float,
                **args: Any) -> None:
    """Buffer one completed span (wall-clock seconds, local clock; the
    GCS offset is applied at drain time).  Bounded: the oldest spans
    drop when the buffer outpaces the flush loop."""
    if not enabled():
        return
    global _spans, _span_cap_applied
    if not _span_cap_applied:
        _span_cap_applied = True
        cap = _span_cap()
        if cap != _spans.maxlen:
            _spans = deque(_spans, maxlen=cap)
    _spans.append({"cat": cat, "name": name, "start": start, "end": end,
                   "pid": os.getpid(), "args": args})


def drain_spans(source: str) -> List[Dict[str, Any]]:
    """Pop buffered spans, clock-corrected onto the GCS timebase and
    stamped with their source process."""
    if not _spans:
        return []
    off = _clock_offset_s
    out = []
    while _spans:
        s = _spans.popleft()
        s["start"] += off
        s["end"] += off
        s["source"] = source
        out.append(s)
    return out


def set_clock_offset(offset_s: float) -> None:
    global _clock_offset_s
    _clock_offset_s = offset_s


def clock_offset() -> float:
    return _clock_offset_s


async def measure_clock_offset(gcs_conn, probes: int = 3
                               ) -> Optional[float]:
    """NTP-style offset of this process's wall clock vs the GCS's:
    ``offset = gcs_time - (t0 + t1) / 2`` over the minimum-RTT probe
    (the tightest round trip bounds the error by rtt/2).  Stored via
    :func:`set_clock_offset` on success; returns the measured offset,
    or None when EVERY probe failed (previous offset kept) — callers
    must retry later rather than treating the process as synced."""
    best_rtt = None
    best_off = None
    for _ in range(probes):
        try:
            t0 = time.time()
            reply = await gcs_conn.call("clock_sync", {}, timeout=5.0)
            t1 = time.time()
        except Exception:  # noqa: BLE001 — unreachable GCS: keep old
            continue
        rtt = t1 - t0
        if best_rtt is None or rtt < best_rtt:
            best_rtt = rtt
            best_off = reply["time"] - (t0 + t1) / 2.0
    if best_off is None:
        return None
    set_clock_offset(best_off)
    return best_off
