"""Node process orchestration: bring-up of head and worker nodes.

Parity: reference ``python/ray/_private/node.py`` + ``services.py`` —
spawn/monitor the per-node daemons and the cluster head.  Here a *head
node* process hosts the GCS and a raylet in one asyncio loop; additional
*worker node* processes host one raylet each.  ``ray_tpu.init()`` spawns a
head subprocess and connects the driver to it; test clusters add more
node subprocesses (see ``ray_tpu.cluster_utils``).

The head writes a small JSON handshake file into the session dir once its
services are listening so the parent can discover the ports.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import signal
import subprocess
import sys
import tempfile
import time
import uuid
from typing import Any, Dict, Optional, Tuple

from ray_tpu.core.config import Config

logger = logging.getLogger(__name__)


def new_session_dir(config: Config) -> str:
    root = config.session_root
    os.makedirs(root, exist_ok=True)
    session = os.path.join(
        root, f"session_{time.strftime('%Y%m%d-%H%M%S')}_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def detect_tpu_resources() -> Dict[str, float]:
    """TPU chips visible on this host, as schedulable resources.

    The chip count comes from env (set by TPU VMs) or an explicit
    override; importing jax here is deliberately avoided since the raylet
    must not grab the accelerator.
    """
    n = os.environ.get("RAY_TPU_CHIPS")
    if n is not None:
        return {"TPU": float(n)} if float(n) > 0 else {}
    accel = os.environ.get("TPU_ACCELERATOR_TYPE")  # e.g. "v5e-8"
    if accel and "-" in accel:
        try:
            return {"TPU": float(accel.rsplit("-", 1)[1])}
        except ValueError:
            pass
    chips = os.environ.get("TPU_CHIPS_PER_HOST_BOUNDS")  # e.g. "2,2,1"
    if chips:
        try:
            dims = [int(x) for x in chips.split(",")]
            total = 1
            for d in dims:
                total *= d
            return {"TPU": float(total)}
        except ValueError:
            pass
    return {}


def detect_topology() -> Dict[str, Any]:
    """Slice/host coordinates for gang scheduling (SURVEY.md §7.2)."""
    topo: Dict[str, Any] = {}
    if os.environ.get("TPU_NAME"):
        topo["slice"] = os.environ["TPU_NAME"]
    if os.environ.get("TPU_WORKER_ID"):
        try:
            topo["worker_index"] = int(os.environ["TPU_WORKER_ID"])
        except ValueError:
            pass
    if os.environ.get("TPU_ACCELERATOR_TYPE"):
        topo["accelerator_type"] = os.environ["TPU_ACCELERATOR_TYPE"]
    return topo


def _write_handshake(path: str, payload: Dict[str, Any]) -> None:
    """Write the session handshake file atomically (tmp + rename).
    Sync on purpose: callers are async and run it in an executor so the
    raylet/GCS loop never blocks on filesystem latency."""
    with open(path + ".tmp", "w") as f:
        json.dump(payload, f)
    os.replace(path + ".tmp", path)


async def _publish_handshake(handshake_path: str, raylet: "Raylet",
                             gcs_address: Tuple[str, int],
                             raylet_address: Tuple[str, int],
                             session_dir: str) -> None:
    """One handshake schema for head and worker nodes — consumers
    (connect(), the CLI) must never need to care which wrote it."""
    await asyncio.get_running_loop().run_in_executor(
        None, _write_handshake, handshake_path, {
            "gcs_address": list(gcs_address),
            "raylet_address": list(raylet_address),
            "node_id": raylet.node_id.hex(),
            "store_path": raylet.store.path,
            "store_capacity": raylet.store_capacity,
            "session_dir": session_dir,
        })


async def run_head(config: Config, session_dir: str,
                   resources: Optional[Dict[str, float]],
                   handshake_path: str, host: str = "127.0.0.1",
                   gcs_port: int = 0) -> None:
    from ray_tpu.core.gcs import GcsServer
    from ray_tpu.core.raylet import Raylet

    # durable GCS tables: kv/jobs/functions/detached actors survive a
    # head restart (reference: GCS recovery from Redis,
    # test_gcs_fault_tolerance.py); the snapshot lives in the session dir
    gcs = GcsServer(config, host=host, port=gcs_port,
                    snapshot_path=os.path.join(session_dir,
                                               "gcs_snapshot.pkl"),
                    session_dir=session_dir)
    gcs_address = await gcs.start()
    merged = dict(resources or {})
    for k, v in detect_tpu_resources().items():
        merged.setdefault(k, v)
    raylet = Raylet(config, gcs_address, session_dir, resources=merged,
                    topology=detect_topology(), host=host)
    raylet_address = await raylet.start()
    _spawn_dashboard_agent(session_dir, raylet.node_id.hex(),
                           gcs_address, config, host=host)
    await _publish_handshake(handshake_path, raylet, gcs_address,
                             raylet_address, session_dir)
    stop = asyncio.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        asyncio.get_running_loop().add_signal_handler(sig, stop.set)
    await stop.wait()
    await raylet.stop()
    await gcs.stop()


async def run_node(config: Config, gcs_address: Tuple[str, int],
                   session_dir: str, resources: Optional[Dict[str, float]],
                   handshake_path: str, host: str = "127.0.0.1") -> None:
    from ray_tpu.core.raylet import Raylet

    merged = dict(resources or {})
    for k, v in detect_tpu_resources().items():
        merged.setdefault(k, v)
    raylet = Raylet(config, gcs_address, session_dir, resources=merged,
                    topology=detect_topology(), host=host)
    raylet_address = await raylet.start()
    _spawn_dashboard_agent(session_dir, raylet.node_id.hex(),
                           gcs_address, config, host=host)
    await _publish_handshake(handshake_path, raylet, gcs_address,
                             raylet_address, session_dir)
    stop = asyncio.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        asyncio.get_running_loop().add_signal_handler(sig, stop.set)
    await stop.wait()
    await raylet.stop()




def _spawn_dashboard_agent(session_dir: str, node_id_hex: str,
                           gcs_address, config: Config,
                           host: str = "127.0.0.1"):
    """Per-node dashboard agent (reference dashboard/agent.py): serves
    node-local stats/logs over HTTP on the node's host address and
    registers itself in the GCS KV.  Spawned through _spawn so it gets
    the same env-stash/PDEATHSIG/posix_spawn discipline as the other
    daemons (it dies with this node process)."""
    if not getattr(config, "dashboard_agent", True):
        return None
    cmd = [sys.executable, "-m", "ray_tpu.dashboard_agent",
           "--session-dir", session_dir,
           "--node-id", node_id_hex,
           "--host", host,
           "--gcs", f"{gcs_address[0]}:{gcs_address[1]}"]
    try:
        return _spawn(cmd, session_dir, f"dashboard-agent-{node_id_hex[:8]}",
                      die_with_parent=safe_die_with_parent())
    except Exception:  # noqa: BLE001 — observability must not block boot
        logging.getLogger(__name__).exception(
            "dashboard agent failed to start")
        return None



def safe_die_with_parent() -> bool:
    """PDEATHSIG fires when the spawning THREAD exits, not the process
    (man prctl) — only arm it when spawning from the main thread, else a
    driver calling init() from a short-lived worker thread would have its
    cluster SIGTERMed when that thread finishes."""
    import threading

    return threading.current_thread() is threading.main_thread()


def preexec_die_with_parent():
    """preexec_fn: SIGTERM this child when its parent dies (Linux
    PR_SET_PDEATHSIG).  Driver-owned clusters must not orphan their head
    when the driver is SIGKILLed; CLI-started daemons do NOT use this
    (a ``ray-tpu start`` cluster outlives the CLI process).  Callers
    must gate on :func:`safe_die_with_parent`.

    Prefer the env-flag + :func:`maybe_arm_pdeathsig` pair for OUR OWN
    daemons: any preexec_fn forces subprocess down the fork path, and
    forking a process whose sitecustomize started jax's threads is the
    canonical latent-deadlock (and warning spam) in this stack.  This
    preexec variant remains for spawning third-party commands that can't
    arm themselves."""
    try:
        import ctypes
        import signal as sig

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, sig.SIGTERM)  # PR_SET_PDEATHSIG = 1
    except Exception:  # non-Linux: best effort only
        pass


def maybe_arm_pdeathsig() -> None:
    """Child-side PDEATHSIG: called first thing in daemon/worker mains
    when the spawner set ``RAY_TPU_PDEATHSIG=<spawner pid>``.  Keeps the
    Popen call preexec_fn-free so CPython can use posix_spawn(3) instead
    of fork+exec (the spawning driver has jax threads running).  The
    spawn→arm window is covered by re-checking getppid() against the
    spawner's pid (NOT against 1 — a containerized driver legitimately
    runs as PID 1, and a reparented orphan may land on a subreaper)."""
    val = os.environ.pop("RAY_TPU_PDEATHSIG", None)
    if not val:
        return
    try:
        import ctypes
        import signal as sig

        libc = ctypes.CDLL(None, use_errno=True)
        libc.prctl(1, sig.SIGTERM)  # PR_SET_PDEATHSIG = 1
        try:
            spawner = int(val)
        except ValueError:
            return
        if os.getppid() != spawner:  # parent died inside the window
            os._exit(1)
    except Exception:  # non-Linux: best effort only
        pass


def spawn_head(config: Config, session_dir: str,
               resources: Optional[Dict[str, float]] = None,
               gcs_port: int = 0, die_with_parent: bool = False,
               ) -> Tuple[subprocess.Popen, Dict[str, Any]]:
    """Spawn the head node subprocess; returns (proc, handshake)."""
    handshake = os.path.join(session_dir, "head_handshake.json")
    if os.path.exists(handshake):  # restart: await a FRESH handshake
        os.remove(handshake)
    cmd = [sys.executable, "-m", "ray_tpu.core.node",
           "--mode", "head",
           "--session-dir", session_dir,
           "--handshake", handshake,
           "--config", config.to_json()]
    if resources is not None:
        cmd += ["--resources", json.dumps(resources)]
    if gcs_port:
        cmd += ["--gcs-port", str(gcs_port)]
    proc = _spawn(cmd, session_dir, "head", die_with_parent)
    return proc, _await_handshake(proc, handshake)


def spawn_node(config: Config, session_dir: str,
               gcs_address: Tuple[str, int],
               resources: Optional[Dict[str, float]] = None,
               die_with_parent: bool = False,
               ) -> Tuple[subprocess.Popen, Dict[str, Any]]:
    handshake = os.path.join(
        session_dir, f"node_handshake_{uuid.uuid4().hex[:8]}.json")
    cmd = [sys.executable, "-m", "ray_tpu.core.node",
           "--mode", "node",
           "--gcs", f"{gcs_address[0]}:{gcs_address[1]}",
           "--session-dir", session_dir,
           "--handshake", handshake,
           "--config", config.to_json()]
    if resources is not None:
        cmd += ["--resources", json.dumps(resources)]
    proc = _spawn(cmd, session_dir, "node", die_with_parent)
    return proc, _await_handshake(proc, handshake)


def _spawn(cmd, session_dir: str, tag: str,
           die_with_parent: bool = False) -> subprocess.Popen:
    log_base = os.path.join(session_dir, "logs",
                            f"{tag}-{uuid.uuid4().hex[:8]}")
    out = open(log_base + ".out", "ab")
    err = open(log_base + ".err", "ab")
    env = dict(os.environ)
    # daemons must import ray_tpu regardless of the driver's cwd
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = pkg_root + (
        ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    # Node daemons never need an accelerator; dropping the axon pool var
    # ALSO keeps sitecustomize from importing jax in the daemon, so its
    # own worker forks stay thread-free.  The originals are STASHED so
    # the raylet can restore them for workers that lease TPU chips
    # (without the stash, every worker inherited the daemon's
    # JAX_PLATFORMS=cpu and could never see the accelerator).
    if os.environ.get("JAX_PLATFORMS"):
        env["RAY_TPU_STASH_JAX_PLATFORMS"] = os.environ["JAX_PLATFORMS"]
    env["JAX_PLATFORMS"] = "cpu"
    pool_ips = env.pop("PALLAS_AXON_POOL_IPS", None)
    if pool_ips:
        env["RAY_TPU_STASH_AXON_POOL_IPS"] = pool_ips
    if die_with_parent:
        # armed child-side (maybe_arm_pdeathsig); value = our pid so the
        # child can detect a parent that died before it armed
        env["RAY_TPU_PDEATHSIG"] = str(os.getpid())
    # close_fds=False + no preexec_fn + no cwd → CPython uses
    # posix_spawn(3): never forks this (jax-threaded) driver process.
    # PEP 446 makes Python-created fds CLOEXEC, so not closing is safe.
    proc = subprocess.Popen(
        cmd, stdout=out, stderr=err, env=env, close_fds=False)
    proc._rtpu_err_path = log_base + ".err"  # for handshake diagnostics
    return proc


def _await_handshake(proc: subprocess.Popen, path: str,
                     timeout: float = 60.0) -> Dict[str, Any]:
    # 60s: heavily loaded CI boxes (full-suite runs with TF/torch tests
    # hogging cores) have shown >30s fork-to-listen latency
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        if proc.poll() is not None:
            raise RuntimeError(
                f"node process exited with code {proc.returncode} before "
                f"handshake: {_stderr_tail(proc)}")
        time.sleep(0.02)
    proc.terminate()
    raise TimeoutError("timed out waiting for node handshake")


def _stderr_tail(proc: subprocess.Popen, limit: int = 2000) -> str:
    """Last bytes of the daemon's .err log for exception messages."""
    try:
        err = getattr(proc, "_rtpu_err_path", None)
        if err and os.path.exists(err):
            with open(err, "rb") as f:
                f.seek(max(0, os.path.getsize(err) - limit))
                return f.read().decode(errors="replace").strip() \
                    or "(empty stderr)"
    except OSError:
        pass
    return "see logs in the session dir"


def main() -> None:
    maybe_arm_pdeathsig()
    parser = argparse.ArgumentParser()
    parser.add_argument("--mode", choices=["head", "node"], required=True)
    parser.add_argument("--gcs", default=None)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--handshake", required=True)
    parser.add_argument("--config", required=True)
    parser.add_argument("--resources", default=None)
    parser.add_argument("--gcs-port", type=int, default=0)
    args = parser.parse_args()

    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    config = Config.from_json(args.config)
    resources = json.loads(args.resources) if args.resources else None
    if args.mode == "head":
        asyncio.run(run_head(config, args.session_dir, resources,
                             args.handshake, gcs_port=args.gcs_port))
    else:
        host, port = args.gcs.rsplit(":", 1)
        asyncio.run(run_node(config, (host, int(port)), args.session_dir,
                             resources, args.handshake))


if __name__ == "__main__":
    main()
