"""Pluggable persistence for the GCS tables.

Parity: reference ``src/ray/gcs/gcs_server/gcs_table_storage.h:261``
(typed table storage behind the GCS) over
``store_client/redis_store_client.h:28`` / ``in_memory_store_client.h``
— the GCS writes through an interface and deployments choose the
backend.  Here:

- ``memory``  — no persistence (explicit ephemeral clusters, tests),
- ``file``    — pickle snapshot in the session dir (same-host restart),
- ``<uri>``   — ``ray_tpu.air.storage`` URI (``file://`` shared
  filesystem today, cloud schemes via ``register_storage``) — survives
  losing the head's DISK/HOST, the gap the session-dir file can't cover.

The unit of storage is the whole-table snapshot dict: the GCS state is
small (control metadata, not data-plane objects), and snapshot-at-once
keeps crash atomicity trivial (single rename/replace).
"""

from __future__ import annotations

import logging
import os
import pickle
import time
from typing import Any, Dict, Optional

logger = logging.getLogger(__name__)


class TableStorage:
    """Interface: load the last snapshot, store a new one.

    ``store`` returns True on success — the GCS only truncates its
    write-ahead log against a snapshot that actually landed; failures
    are also counted (``ray_tpu_gcs_persist_failures_total``) and
    surfaced through ``debug_state``/``ray-tpu status`` instead of
    being a log line nobody reads.
    """

    #: wall-clock time of the last successful store (0 = never)
    last_persist_ts: float = 0.0
    #: store() failures since boot (mirrors the metrics counter)
    persist_failures: int = 0

    def load(self) -> Optional[Dict[str, Any]]:
        raise NotImplementedError

    def store(self, snapshot: Dict[str, Any]) -> bool:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__

    def _stored_ok(self) -> bool:
        self.last_persist_ts = time.time()
        return True

    def _store_failed(self, e: BaseException) -> bool:
        self.persist_failures += 1
        logger.warning("GCS table persistence failed on %s: %s",
                       self.describe(), e)
        from ray_tpu.core import telemetry as _tm
        _tm.gcs_persist_failure(type(self).__name__)
        return False


class InMemoryTableStorage(TableStorage):
    """No persistence: a restarted GCS cold-starts (reference
    in-memory store client)."""

    def load(self) -> Optional[Dict[str, Any]]:
        return None

    def store(self, snapshot: Dict[str, Any]) -> bool:
        return True


class FileTableStorage(TableStorage):
    """Session-dir pickle with atomic replace (same-host restarts)."""

    def __init__(self, path: str):
        self.path = path

    def load(self) -> Optional[Dict[str, Any]]:
        if not os.path.exists(self.path):
            return None
        try:
            with open(self.path, "rb") as f:
                return pickle.load(f)
        except Exception as e:  # noqa: BLE001 — a torn snapshot cold-starts
            logger.warning("GCS snapshot unreadable (%s); cold start", e)
            return None

    def store(self, snapshot: Dict[str, Any]) -> bool:
        # single atomic-write implementation lives in air.storage
        from ray_tpu.air.storage import FileStorage as _FS
        try:
            _FS().write_bytes(self.path, pickle.dumps(snapshot))
        except OSError as e:
            return self._store_failed(e)
        return self._stored_ok()

    def describe(self) -> str:
        return f"file:{self.path}"


class URITableStorage(TableStorage):
    """Durable storage through ``ray_tpu.air.storage`` — a head-host
    loss is survivable when the URI lives off-host."""

    def __init__(self, uri: str):
        from ray_tpu.air import storage
        self._storage = storage
        self.uri = storage.join(uri, "gcs_tables.pkl")

    def load(self) -> Optional[Dict[str, Any]]:
        try:
            if not self._storage.exists(self.uri):
                return None
            return pickle.loads(self._storage.read_bytes(self.uri))
        except Exception as e:  # noqa: BLE001
            logger.warning("GCS table storage unreadable (%s); cold start",
                           e)
            return None

    def store(self, snapshot: Dict[str, Any]) -> bool:
        try:
            self._storage.write_bytes(self.uri, pickle.dumps(snapshot))
        except Exception as e:  # noqa: BLE001
            return self._store_failed(e)
        return self._stored_ok()

    def describe(self) -> str:
        return self.uri


def make_table_storage(spec: Optional[str],
                       default_path: Optional[str]) -> TableStorage:
    """Resolve the configured backend (``Config.gcs_table_storage``).

    ``""``/``"file"`` → session-dir file (when a path is known),
    ``"memory"`` → ephemeral, anything with ``://`` → URI storage.
    """
    if spec in (None, "", "file"):
        if default_path:
            return FileTableStorage(default_path)
        return InMemoryTableStorage()
    if spec == "memory":
        return InMemoryTableStorage()
    if "://" in spec:
        return URITableStorage(spec)
    return FileTableStorage(spec)
