"""Device-plane sensors: what is the accelerator actually doing?

Every observability layer before this one watches the *host* — RPC
latencies, CPU stacks, trace spans, SLO burn rates.  A TPU-native
runtime lives or dies by what the *device* does, and three failure
modes are invisible from the host side until they surface as a
tail-latency mystery:

- **recompile storms** — a shape leak past the padding buckets makes
  XLA retrace on every step; throughput collapses while every host
  metric looks healthy;
- **data starvation** — the chip idles between steps waiting on the
  input pipeline; host throughput counters keep climbing because the
  host *is* busy — shoveling;
- **gang stragglers** — one slow rank gates every step of a gang
  (network, a noisy neighbor, thermal throttling); the gang's
  aggregate step time degrades with no per-replica signal naming the
  culprit.

Three instruments, one per failure mode:

``instrument_step(fn, name)``
    Wraps a jitted step entry point.  Each call's *abstract input
    signature* (shapes + dtypes, not values) is keyed against the
    wrapper's seen-set — a miss is exactly when ``jax.jit`` compiles —
    and timed, emitting ``ray_tpu_xla_compiles_total{fn,reason}`` +
    ``ray_tpu_xla_compile_seconds`` plus a ``compile`` span into the
    tracing plane.  Steady-state calls cost one set lookup.

:class:`StepMonitor`
    Splits each step's wall time into the data_wait / host / device /
    sync phase ladder (device time via ``block_until_ready``
    bracketing), derives rolling MFU and goodput from engine-declared
    FLOPs-per-token, and exports the ``train:mfu`` /
    ``train:step_data_wait_frac`` / ``serve:decode_device_frac``
    recording-rule inputs.  Phases telescope to step wall time by
    construction: every boundary is a stamp of the same clock.

:class:`RankSkewWindow`
    Gang-level straggler detector: per-rank step durations feed a
    rolling window; skew = max - min of the per-rank means, and the
    argmax rank is named in ``ray_tpu_gang_rank_skew_seconds``'s
    ``straggler`` tag (which the GangStraggler alert's group_by
    surfaces) and in ``gang``-category trace spans.

The module must stay import-cheap (no jax import at module load): the
worker imports it on every task execution to attribute device seconds
into the ``task_exec`` span (`ray-tpu analyze`'s exec_host/exec_device
split).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu.core import telemetry as _tm

__all__ = ["instrument_step", "is_instrumented", "compile_count",
           "compile_stats", "StepMonitor", "RankSkewWindow",
           "peak_flops_per_chip", "device_seconds",
           "add_device_seconds", "reset_for_tests"]


def peak_flops_per_chip() -> float:
    """Best-effort peak bf16 FLOPs of the attached chip (the MFU
    denominator).  CPU hosts get the v5e figure so CPU-smoke MFU
    numbers stay comparable across bench runs."""
    try:
        import jax

        kind = jax.devices()[0].device_kind.lower()
    except Exception:  # noqa: BLE001 — no backend: assume v5e-class
        return 197e12
    table = {
        "v5 lite": 197e12, "v5e": 197e12, "v5litepod": 197e12,
        "v4": 275e12,
        "v5p": 459e12,
        "v6 lite": 918e12, "v6e": 918e12,
    }
    for key, val in table.items():
        if key in kind:
            return val
    return 197e12


# ---------------------------------------------------------------------------
# XLA compile accounting
# ---------------------------------------------------------------------------

_compile_lock = threading.Lock()
#: fn name -> {"total": int, "first": int, "shape_miss": int,
#:             "seconds": float}
_compiles: Dict[str, Dict[str, Any]] = {}


def _abstract(x: Any) -> Any:
    """Abstract one argument the way jit's cache keys it: arrays by
    (shape, dtype), containers structurally, python scalars by type
    only (jit re-traces on *type* changes, not value changes)."""
    shape = getattr(x, "shape", None)
    if shape is not None:
        return ("arr", tuple(shape), str(getattr(x, "dtype", "?")))
    if isinstance(x, (list, tuple)):
        return ("seq", tuple(_abstract(v) for v in x))
    if isinstance(x, dict):
        return ("map", tuple(sorted(
            (str(k), _abstract(v)) for k, v in x.items())))
    return ("py", type(x).__name__)


def _record_compile(name: str, reason: str, seconds: float) -> None:
    with _compile_lock:
        st = _compiles.get(name)
        if st is None:
            st = _compiles[name] = {"total": 0, "first": 0,
                                    "shape_miss": 0, "seconds": 0.0}
        st["total"] += 1
        st[reason] = st.get(reason, 0) + 1
        st["seconds"] += seconds


def instrument_step(fn: Callable, name: str) -> Callable:
    """Wrap a jitted step entry point with compile detection.

    A call whose abstract input signature was never seen by THIS
    wrapper is a compilation (``jax.jit`` keys its executable cache the
    same way): the first signature is ``reason="first"``, every later
    new signature is a ``shape_miss`` recompile.  The wrapper is
    rebuilt together with the jit it wraps (e.g. on a weight swap that
    re-traces), so wrapper-seen-set and jit-cache stay in lockstep —
    the toy decoder's ``trace_count`` discipline cross-checks this in
    tests.  Compile seconds are the traced call's wall time including
    its first execution (the cost a request actually pays)."""
    seen: set = set()
    lock = threading.Lock()

    def wrapped(*args, **kwargs):
        sig = (_abstract(args), _abstract(kwargs) if kwargs else None)
        with lock:
            is_new = sig not in seen
            if is_new:
                reason = "first" if not seen else "shape_miss"
                seen.add(sig)
        if not is_new:
            return fn(*args, **kwargs)
        t0 = time.time()
        out = fn(*args, **kwargs)
        try:
            import jax

            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — non-array out: timed as-is
            pass
        t1 = time.time()
        _record_compile(name, reason, t1 - t0)
        _tm.xla_compile(name, reason, t1 - t0)
        _tm.record_span("compile", name, t0, t1, reason=reason)
        return out

    wrapped._rtpu_instrumented = True  # step-instrumentation rule hook
    wrapped._rtpu_step_name = name
    wrapped.__wrapped__ = fn
    return wrapped


def is_instrumented(fn: Callable) -> bool:
    return bool(getattr(fn, "_rtpu_instrumented", False))


def compile_count(name: Optional[str] = None) -> int:
    """Compilations recorded in this process (one fn, or all)."""
    with _compile_lock:
        if name is not None:
            st = _compiles.get(name)
            return int(st["total"]) if st else 0
        return sum(int(st["total"]) for st in _compiles.values())


def compile_stats() -> Dict[str, Dict[str, Any]]:
    with _compile_lock:
        return {k: dict(v) for k, v in _compiles.items()}


# ---------------------------------------------------------------------------
# per-task device-seconds attribution (ray-tpu analyze exec split)
# ---------------------------------------------------------------------------

_tls = threading.local()


def device_seconds() -> float:
    """Device-compute seconds accumulated on THIS thread.  The worker
    snapshots the value around a task body; the delta rides the
    ``task_exec`` span as ``device_s`` so `ray-tpu analyze` can split
    ``exec`` into host and device time."""
    return getattr(_tls, "device_s", 0.0)


def add_device_seconds(seconds: float) -> None:
    if seconds > 0:
        _tls.device_s = getattr(_tls, "device_s", 0.0) + seconds


# ---------------------------------------------------------------------------
# step-time attribution
# ---------------------------------------------------------------------------

class _StepSpan:
    """Phase stamps of one step; every boundary is a ``time.time()``
    stamp, so the recorded phases telescope to the step's wall time
    exactly (the 5% acceptance gate only absorbs clock granularity)."""

    __slots__ = ("_mon", "_t0", "_t_host", "_t_dev", "_data_wait")

    def __init__(self, mon: "StepMonitor", data_wait_s: float):
        self._mon = mon
        self._data_wait = max(0.0, float(data_wait_s))
        self._t0 = time.time()
        self._t_host: Optional[float] = None
        self._t_dev: Optional[float] = None

    def dispatched(self) -> None:
        """The jitted call returned: host dispatch ends, device-compute
        bracketing starts."""
        self._t_host = time.time()

    def device_done(self, out: Any = None) -> Any:
        """Block until ``out`` is ready and stamp the device boundary.
        Returns ``out`` so call sites can chain."""
        if out is not None:
            try:
                import jax

                jax.block_until_ready(out)
            except Exception:  # noqa: BLE001 — host array: already done
                pass
        self._t_dev = time.time()
        return out

    def done(self, *, tokens: float = 0.0, requests: float = 0.0) -> None:
        t_end = time.time()
        t_host = self._t_host if self._t_host is not None else t_end
        t_dev = self._t_dev if self._t_dev is not None else t_host
        self._mon.record_step(
            data_wait_s=self._data_wait,
            host_s=max(0.0, t_host - self._t0),
            device_s=max(0.0, t_dev - t_host),
            sync_s=max(0.0, t_end - t_dev),
            tokens=tokens, requests=requests)


class StepMonitor:
    """Per-engine step-time attribution: the data_wait / host / device
    / sync phase ladder, rolling MFU, and goodput.

    ``plane`` routes the exported gauges: ``train`` feeds
    ``ray_tpu_train_mfu`` + ``ray_tpu_train_step_data_wait_frac``,
    ``serve`` feeds ``ray_tpu_serve_decode_device_frac{deployment}``,
    every plane feeds the ``ray_tpu_step_phase_seconds`` histograms
    and the goodput gauge.  MFU needs ``flops_per_token`` from the
    engine (0 disables it — goodput and phase fractions still work).
    """

    PHASES = ("data_wait", "host", "device", "sync")

    def __init__(self, plane: str, name: str = "", *,
                 deployment: str = "", flops_per_token: float = 0.0,
                 peak_flops: Optional[float] = None, window: int = 256):
        self.plane = plane
        self.name = name or plane
        self.deployment = deployment
        self.flops_per_token = float(flops_per_token)
        self.peak_flops = float(peak_flops) if peak_flops \
            else peak_flops_per_chip()
        self._lock = threading.Lock()
        self._window: "deque[Tuple[float, float, float, float, float]]" \
            = deque(maxlen=max(8, window))
        self._steps = 0
        self._sums = dict.fromkeys(self.PHASES, 0.0)
        self._tokens = 0.0
        self._requests = 0.0

    def step(self, data_wait_s: float = 0.0) -> _StepSpan:
        """Open one step's phase bracket (see :class:`_StepSpan`)."""
        return _StepSpan(self, data_wait_s)

    def record_step(self, *, data_wait_s: float = 0.0,
                    host_s: float = 0.0, device_s: float = 0.0,
                    sync_s: float = 0.0, tokens: float = 0.0,
                    requests: float = 0.0) -> None:
        """Record one step's phase split directly (engines that own
        their own stamps); :meth:`step` brackets funnel here."""
        with self._lock:
            self._steps += 1
            self._sums["data_wait"] += data_wait_s
            self._sums["host"] += host_s
            self._sums["device"] += device_s
            self._sums["sync"] += sync_s
            self._tokens += tokens
            self._requests += requests
            self._window.append((data_wait_s, host_s, device_s, sync_s,
                                 tokens))
            mfu, goodput, dev_frac, wait_frac = self._derive_locked()
        add_device_seconds(device_s)
        _tm.step_phase(self.plane, "data_wait", data_wait_s)
        _tm.step_phase(self.plane, "host", host_s)
        _tm.step_phase(self.plane, "device", device_s)
        _tm.step_phase(self.plane, "sync", sync_s)
        _tm.step_goodput(self.plane, goodput)
        if self.plane == "train":
            _tm.train_step_quality(mfu, wait_frac)
        elif self.plane == "serve" and self.deployment:
            _tm.serve_decode_device_frac(self.deployment, dev_frac)

    def _derive_locked(self) -> Tuple[float, float, float, float]:
        wait = host = dev = sync = tok = 0.0
        for dw, h, d, s, t in self._window:
            wait += dw
            host += h
            dev += d
            sync += s
            tok += t
        wall = wait + host + dev + sync
        if wall <= 0:
            return 0.0, 0.0, 0.0, 0.0
        goodput = tok / wall
        mfu = (goodput * self.flops_per_token / self.peak_flops) \
            if self.flops_per_token > 0 else 0.0
        return mfu, goodput, dev / wall, wait / wall

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            mfu, goodput, dev_frac, wait_frac = self._derive_locked()
            wall = sum(self._sums.values())
            return {
                "steps": self._steps,
                "phase_s": dict(self._sums),
                "wall_s": wall,
                "tokens": self._tokens,
                "requests": self._requests,
                "mfu": mfu,
                "goodput_per_s": goodput,
                "device_frac": dev_frac,
                "data_wait_frac": wait_frac,
            }


# ---------------------------------------------------------------------------
# gang straggler detection
# ---------------------------------------------------------------------------

class RankSkewWindow:
    """Rolling per-rank step durations of one gang; skew is the spread
    of the per-rank means over the window, and the straggler is the
    argmax rank.  Rank 0 (the gang driver) records everyone's duration
    per step — its own slice's compute time plus each remote rank's
    submit-to-arrival time — so no shard-protocol change is needed."""

    def __init__(self, world: int, window: int = 64):
        self.world = int(world)
        self._lock = threading.Lock()
        self._durs: List["deque[float]"] = [
            deque(maxlen=max(8, window)) for _ in range(self.world)]

    def record(self, durations_s: Dict[int, float]) -> None:
        with self._lock:
            for rank, dur in durations_s.items():
                if 0 <= int(rank) < self.world:
                    self._durs[int(rank)].append(float(dur))

    def snapshot(self) -> Dict[str, Any]:
        """{"rank_step_s": [...], "skew_s": float, "straggler": int}
        — means over the window; empty ranks report 0 and a gang with
        fewer than two reporting ranks has zero skew."""
        with self._lock:
            means = [(sum(d) / len(d)) if d else 0.0
                     for d in self._durs]
        reporting = [m for m in means if m > 0]
        if len(reporting) < 2:
            return {"rank_step_s": means, "skew_s": 0.0, "straggler": 0}
        skew = max(reporting) - min(reporting)
        straggler = max(range(len(means)), key=lambda r: means[r])
        return {"rank_step_s": means, "skew_s": skew,
                "straggler": straggler}


def reset_for_tests() -> None:
    """Clear process-global compile accounting (test isolation)."""
    with _compile_lock:
        _compiles.clear()
    _tls.device_s = 0.0
