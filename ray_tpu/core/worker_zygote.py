"""Worker fork-server ("zygote"): amortize interpreter start + imports.

Parity rationale: the reference prestarts pooled C++-backed workers
(``worker_pool.h:156`` prestart) because process start dominates
small-actor creation; in pure Python the equivalent lever is a fork
server — one template process pays interpreter boot + ``ray_tpu.core``
imports (~300 ms cold), then each worker is an ``os.fork()`` (~10 ms).
The raylet talks to it over a line-oriented stdin/stdout protocol:

    -> {"argv": [...], "env": {...}, "log_base": "..."}
    <- {"pid": 12345}

Safety: the zygote imports only thread-free modules (threads, event
loops, and sockets all start inside ``CoreWorker.__init__`` AFTER the
fork), and ``ray_tpu.core.ids`` re-seeds its entropy pool via
``os.register_at_fork``.  TPU-capable workers do NOT fork from here —
they need the accelerator plugin's sitecustomize, which only runs at
real interpreter start — so the raylet uses this path only for plain
(CPU) pool workers.
"""

from __future__ import annotations

import json
import os
import signal
import sys


def _child(req: dict) -> None:
    os.setsid()  # own process group; raylet kills by pid
    # No PDEATHSIG here: tying workers to the ZYGOTE's lifetime would
    # kill every live actor if the zygote crashed.  Orphan protection is
    # the worker's raylet-connection watch (worker.py exits on close).
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)  # undo zygote's IGN —
    # user task code must see real subprocess exit statuses
    devnull = os.open(os.devnull, os.O_RDONLY)
    os.dup2(devnull, 0)  # NEVER share the zygote control pipe with tasks
    os.close(devnull)
    out = open(req["log_base"] + ".out", "ab", buffering=0)
    err = open(req["log_base"] + ".err", "ab", buffering=0)
    os.dup2(out.fileno(), 1)
    os.dup2(err.fileno(), 2)
    for key, value in req.get("env", {}).items():
        if value is None:
            os.environ.pop(key, None)
        else:
            os.environ[key] = str(value)
    sys.argv = ["ray_tpu-worker"] + list(req["argv"])
    from ray_tpu.core import worker_main

    code = 0
    try:
        worker_main.main()
    except SystemExit as e:
        code = int(e.code or 0)
    except BaseException:
        import traceback

        traceback.print_exc()
        code = 1
    finally:
        os._exit(code)


def main() -> None:
    from ray_tpu.core.node import maybe_arm_pdeathsig
    maybe_arm_pdeathsig()
    # Pre-warm the import graph forks inherit.  Deliberately NOT jax —
    # plain pool workers never touch the accelerator.
    import ray_tpu.core.worker  # noqa: F401 — pulls rpc/serialization/ids
    import ray_tpu.actor  # noqa: F401
    import ray_tpu.remote_function  # noqa: F401

    # Freeze the template heap (the fork-server trick): a child's first
    # gc pass otherwise writes mark bits into EVERY inherited object's
    # header, copy-on-write-faulting the whole template heap per worker
    # — a large slice of per-fork boot cost during actor creation storms.
    import gc
    gc.collect()
    gc.freeze()

    # reap forked children so they don't accumulate as zombies
    signal.signal(signal.SIGCHLD, signal.SIG_IGN)

    sys.stdout.write(json.dumps({"ready": True}) + "\n")
    sys.stdout.flush()
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        try:
            req = json.loads(line)
        except json.JSONDecodeError:
            continue
        if req.get("exit"):
            break
        pid = os.fork()
        if pid == 0:
            _child(req)  # never returns
        sys.stdout.write(json.dumps({"pid": pid}) + "\n")
        sys.stdout.flush()


if __name__ == "__main__":
    main()
