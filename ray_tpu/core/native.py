"""ctypes loader for the native (C++) runtime components.

The native sources live in ``src/`` at the repo root and are compiled to a
shared library on first use (cached by source mtime), or ahead of time via
``make`` / ``python -m ray_tpu.core.native``.  ctypes rather than an
extension module keeps the build a single ``g++`` invocation with no
Python-dev dependency (pybind11 is unavailable in this environment).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC_DIR = os.path.join(_REPO_ROOT, "src")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "librtpu.so")
_SOURCES = ["object_store.cc", "sched_core.cc"]

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, s)) > lib_mtime for s in _SOURCES
    )


def build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-std=c++17", "-O2", "-g", "-fPIC", "-shared",
        "-Wall", "-Wextra",
        *[os.path.join(_SRC_DIR, s) for s in _SOURCES],
        "-o", _LIB_PATH + ".tmp",
        "-pthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(_LIB_PATH + ".tmp", _LIB_PATH)
    return _LIB_PATH


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            build()
        lib = ctypes.CDLL(_LIB_PATH)
        u64 = ctypes.c_uint64
        p_u64 = ctypes.POINTER(u64)
        buf = ctypes.c_char_p  # 28-byte id blobs pass as bytes

        lib.rtpu_store_create.restype = ctypes.c_void_p
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, u64]
        lib.rtpu_store_destroy.restype = None
        lib.rtpu_store_destroy.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_put.restype = ctypes.c_int64
        lib.rtpu_store_put.argtypes = [ctypes.c_void_p, buf, u64]
        lib.rtpu_store_put_hint.restype = ctypes.c_int64
        lib.rtpu_store_put_hint.argtypes = [ctypes.c_void_p, buf, u64, u64]
        lib.rtpu_store_seal.restype = ctypes.c_int
        lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_get.restype = ctypes.c_int
        lib.rtpu_store_get.argtypes = [ctypes.c_void_p, buf, p_u64, p_u64]
        lib.rtpu_store_release.restype = ctypes.c_int
        lib.rtpu_store_release.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_contains.restype = ctypes.c_int
        lib.rtpu_store_contains.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_delete.restype = ctypes.c_int
        lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_evict.restype = u64
        lib.rtpu_store_evict.argtypes = [ctypes.c_void_p, u64]
        lib.rtpu_store_lru_candidates.restype = u64
        lib.rtpu_store_lru_candidates.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_char_p, u64]
        lib.rtpu_store_stats.restype = None
        lib.rtpu_store_stats.argtypes = [ctypes.c_void_p, p_u64, p_u64, p_u64]
        try:
            # telemetry extensions (absent from a stale pre-built .so;
            # stats_ex callers fall back to the basic stats)
            lib.rtpu_store_stats_ex.restype = u64
            lib.rtpu_store_stats_ex.argtypes = [ctypes.c_void_p, p_u64, u64]
            lib.rtpu_store_bucket_used.restype = u64
            lib.rtpu_store_bucket_used.argtypes = [ctypes.c_void_p, p_u64,
                                                   u64]
            lib.rtpu_store_shard_contention.restype = u64
            lib.rtpu_store_shard_contention.argtypes = [ctypes.c_void_p,
                                                        p_u64, u64]
            lib.rtpu_store_spill_candidates.restype = u64
            lib.rtpu_store_spill_candidates.argtypes = [
                ctypes.c_void_p, ctypes.c_char_p, p_u64, u64, u64]
            lib.rtpu_store_create_sharded.restype = ctypes.c_void_p
            lib.rtpu_store_create_sharded.argtypes = [ctypes.c_char_p,
                                                      u64, u64]
            lib.rtpu_store_used.restype = u64
            lib.rtpu_store_used.argtypes = [ctypes.c_void_p]
        except AttributeError:
            pass

        f64p = ctypes.POINTER(ctypes.c_double)
        i64p = ctypes.POINTER(ctypes.c_int64)
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.rtpu_sched_pick_node.restype = ctypes.c_int
        lib.rtpu_sched_pick_node.argtypes = [
            f64p, i64p, ctypes.c_int, ctypes.c_int, f64p, ctypes.c_int,
            ctypes.c_double, ctypes.c_double, ctypes.c_int]
        lib.rtpu_sched_place_bundles.restype = ctypes.c_int
        lib.rtpu_sched_place_bundles.argtypes = [
            f64p, ctypes.c_int, ctypes.c_int, f64p, ctypes.c_int,
            ctypes.c_int, i32p]
        _lib = lib
        return _lib


# ---------------------------------------------------------------------------
# scheduling-core wrappers (dict-of-resources <-> flat matrices)
# ---------------------------------------------------------------------------

def sched_pick_node(candidates, demand: dict, *, strategy: str,
                    local_utilization: float, spread_threshold: float,
                    local_feasible: bool):
    """C++ hybrid/spread spillback choice.  ``candidates`` is a list of
    (available_resources_dict, load_int); returns the chosen candidate
    index or None (stay local)."""
    lib = load()
    keys = sorted({k for a, _ in candidates for k in a} | set(demand))
    n_nodes, n_res = len(candidates), max(len(keys), 1)
    avail = (ctypes.c_double * (n_nodes * n_res))()
    load_arr = (ctypes.c_int64 * max(n_nodes, 1))()
    for i, (a, load_val) in enumerate(candidates):
        for r, k in enumerate(keys):
            avail[i * n_res + r] = float(a.get(k, 0.0))
        load_arr[i] = int(load_val)
    dem = (ctypes.c_double * n_res)()
    for r, k in enumerate(keys):
        dem[r] = float(demand.get(k, 0.0))
    out = lib.rtpu_sched_pick_node(
        avail, load_arr, n_nodes, n_res, dem,
        1 if strategy == "SPREAD" else 0,
        float(local_utilization), float(spread_threshold),
        1 if local_feasible else 0)
    return None if out < 0 else int(out)


def sched_place_bundles(node_avail, bundles, strategy: str):
    """C++ bundle placement.  ``node_avail``: list of resource dicts in
    the caller's (topology-sorted) node order; ``bundles``: list of
    resource dicts.  Returns a list of node indices or None."""
    lib = load()
    keys = sorted({k for a in node_avail for k in a}
                  | {k for b in bundles for k in b})
    n_nodes, n_res = len(node_avail), max(len(keys), 1)
    n_bundles = len(bundles)
    avail = (ctypes.c_double * (n_nodes * n_res))()
    for i, a in enumerate(node_avail):
        for r, k in enumerate(keys):
            avail[i * n_res + r] = float(a.get(k, 0.0))
    bnd = (ctypes.c_double * max(n_bundles * n_res, 1))()
    for b, bd in enumerate(bundles):
        for r, k in enumerate(keys):
            bnd[b * n_res + r] = float(bd.get(k, 0.0))
    out = (ctypes.c_int32 * max(n_bundles, 1))()
    strategies = {"PACK": 0, "SPREAD": 1, "STRICT_PACK": 2,
                  "STRICT_SPREAD": 3}
    ok = lib.rtpu_sched_place_bundles(
        avail, n_nodes, n_res, bnd, n_bundles, strategies[strategy], out)
    return list(out[:n_bundles]) if ok else None


if __name__ == "__main__":
    print(build())
