"""ctypes loader for the native (C++) runtime components.

The native sources live in ``src/`` at the repo root and are compiled to a
shared library on first use (cached by source mtime), or ahead of time via
``make`` / ``python -m ray_tpu.core.native``.  ctypes rather than an
extension module keeps the build a single ``g++`` invocation with no
Python-dev dependency (pybind11 is unavailable in this environment).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_REPO_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))
_SRC_DIR = os.path.join(_REPO_ROOT, "src")
_BUILD_DIR = os.path.join(_REPO_ROOT, "build")
_LIB_PATH = os.path.join(_BUILD_DIR, "librtpu.so")
_SOURCES = ["object_store.cc"]

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None


def _needs_build() -> bool:
    if not os.path.exists(_LIB_PATH):
        return True
    lib_mtime = os.path.getmtime(_LIB_PATH)
    return any(
        os.path.getmtime(os.path.join(_SRC_DIR, s)) > lib_mtime for s in _SOURCES
    )


def build() -> str:
    os.makedirs(_BUILD_DIR, exist_ok=True)
    cmd = [
        "g++", "-std=c++17", "-O2", "-g", "-fPIC", "-shared",
        "-Wall", "-Wextra",
        *[os.path.join(_SRC_DIR, s) for s in _SOURCES],
        "-o", _LIB_PATH + ".tmp",
        "-pthread",
    ]
    subprocess.run(cmd, check=True, capture_output=True, text=True)
    os.replace(_LIB_PATH + ".tmp", _LIB_PATH)
    return _LIB_PATH


def load() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    with _lock:
        if _lib is not None:
            return _lib
        if _needs_build():
            build()
        lib = ctypes.CDLL(_LIB_PATH)
        u64 = ctypes.c_uint64
        p_u64 = ctypes.POINTER(u64)
        buf = ctypes.c_char_p  # 28-byte id blobs pass as bytes

        lib.rtpu_store_create.restype = ctypes.c_void_p
        lib.rtpu_store_create.argtypes = [ctypes.c_char_p, u64]
        lib.rtpu_store_destroy.restype = None
        lib.rtpu_store_destroy.argtypes = [ctypes.c_void_p]
        lib.rtpu_store_put.restype = ctypes.c_int64
        lib.rtpu_store_put.argtypes = [ctypes.c_void_p, buf, u64]
        lib.rtpu_store_seal.restype = ctypes.c_int
        lib.rtpu_store_seal.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_get.restype = ctypes.c_int
        lib.rtpu_store_get.argtypes = [ctypes.c_void_p, buf, p_u64, p_u64]
        lib.rtpu_store_release.restype = ctypes.c_int
        lib.rtpu_store_release.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_contains.restype = ctypes.c_int
        lib.rtpu_store_contains.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_delete.restype = ctypes.c_int
        lib.rtpu_store_delete.argtypes = [ctypes.c_void_p, buf]
        lib.rtpu_store_evict.restype = u64
        lib.rtpu_store_evict.argtypes = [ctypes.c_void_p, u64]
        lib.rtpu_store_lru_candidates.restype = u64
        lib.rtpu_store_lru_candidates.argtypes = [ctypes.c_void_p,
                                                  ctypes.c_char_p, u64]
        lib.rtpu_store_stats.restype = None
        lib.rtpu_store_stats.argtypes = [ctypes.c_void_p, p_u64, p_u64, p_u64]
        _lib = lib
        return _lib


if __name__ == "__main__":
    print(build())
