"""ObjectRef: a first-class future/handle for a distributed immutable value.

Parity: reference ``python/ray/_raylet.pyx`` ObjectRef + the ownership
model of ``src/ray/core_worker/reference_count.h`` — every ref knows its
*owner* (the worker that created it), which is the authority for the
value's location and lifetime.  Local ref counting is driven by Python
object lifetime: ``__del__`` notifies the core worker, which releases the
object once all local refs, submitted-task refs, and borrows are gone.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ray_tpu.core.ids import ObjectID

# Owner address: (node hint, host, port, worker_id_hex). Kept as a plain
# tuple so it pickles compactly inside task specs.
OwnerAddress = Tuple[str, str, int, str]


class ObjectRef:
    __slots__ = ("_id", "_owner_address", "_registered", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: Optional[OwnerAddress],
                 *, _register: bool = True):
        self._id = object_id
        self._owner_address = owner_address
        self._registered = False
        if _register:
            self._register()

    def _register(self) -> None:
        from ray_tpu.core import worker as worker_mod

        core = worker_mod.global_worker_or_none()
        if core is not None:
            core.reference_counter.add_local_ref(self._id)
            self._registered = True

    # -- identity ---------------------------------------------------------
    def id(self) -> ObjectID:
        return self._id

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def owner_address(self) -> Optional[OwnerAddress]:
        return self._owner_address

    def task_id(self):
        return self._id.task_id()

    # -- convenience ------------------------------------------------------
    def future(self):
        """Return a concurrent.futures.Future resolving to the value."""
        from ray_tpu.core import worker as worker_mod

        return worker_mod.global_worker().get_async(self)

    def __await__(self):
        from ray_tpu.core import worker as worker_mod

        import asyncio

        fut = worker_mod.global_worker().get_async(self)
        return asyncio.wrap_future(fut).__await__()

    def __hash__(self) -> int:
        return hash(self._id)

    def __eq__(self, other) -> bool:
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self) -> str:
        return f"ObjectRef({self._id.hex()})"

    def __del__(self):
        if not self._registered:
            return
        try:
            from ray_tpu.core import worker as worker_mod

            core = worker_mod.global_worker_or_none()
            if core is not None:
                # Deferred: finalizers may run on any thread while it holds
                # unrelated locks; the refcount mutation happens on the io
                # loop (see CoreWorker.deferred_remove_local_ref).
                core.deferred_remove_local_ref(self._id)
        except Exception:
            pass  # interpreter shutdown

    def __reduce__(self):
        # Direct pickling travels through serialization.persistent_id in
        # task specs / values; this path covers ad-hoc pickling and marks
        # the ref restored (borrowed) on the far side.
        return (ObjectRef._restore, (self._id.binary(), self._owner_address))

    @staticmethod
    def _restore(id_bytes: bytes, owner_address: Optional[OwnerAddress]) -> "ObjectRef":
        ref = ObjectRef(ObjectID(id_bytes), owner_address, _register=False)
        from ray_tpu.core import worker as worker_mod

        core = worker_mod.global_worker_or_none()
        if core is not None:
            core.reference_counter.add_borrowed_ref(ref._id, owner_address)
            ref._registered = True
        return ref


class ObjectRefGenerator:
    """The value a ``num_returns="dynamic"`` task resolves to: an
    iterable of the ObjectRefs the task yielded (parity: reference
    ``python/ray/_raylet.pyx:603-622`` ObjectRefGenerator — the static
    form, where the refs are known once the task finished).

    ``get`` on the task's return ref produces one of these; iterating
    yields ObjectRefs that can be ``get``-ed lazily or passed to
    downstream tasks.  The refs travel through the normal serialization
    path, so borrow/ownership tracking applies wherever the generator
    object lands.
    """

    def __init__(self, refs):
        self._refs = list(refs)

    def __iter__(self):
        return iter(self._refs)

    def __len__(self) -> int:
        return len(self._refs)

    def __getitem__(self, i):
        return self._refs[i]

    def __repr__(self) -> str:
        return f"ObjectRefGenerator({len(self._refs)} refs)"


class StreamingObjectRefGenerator:
    """num_returns="streaming" handle: yields each item's ObjectRef as
    the executing task produces it — consumption overlaps execution
    (parity: the reference's streaming generator protocol,
    ``python/ray/_raylet.pyx`` StreamingObjectRefGenerator).

    Iteration blocks until the next item is announced (worker → owner
    push) or the task finishes; a task error raises at the position
    where the stream broke.
    """

    def __init__(self, task_id, core):
        self._task_id = task_id
        self._core = core
        self._consumed = 0

    @property
    def task_id(self):
        return self._task_id

    def __iter__(self):
        return self

    def __next__(self):
        ref = self.next_ref(timeout=None)
        if ref is None:
            raise StopIteration
        return ref

    def next_ref(self, timeout=None):
        """Next item's ObjectRef, or None at end-of-stream.  Raises the
        task's error if it failed before producing another item."""
        state = self._core._streaming_states.get(self._task_id.binary())
        if state is None:
            return None  # never registered / already reaped
        with state.cond:
            while True:
                if self._consumed < len(state.dyn_ids) \
                        and state.dyn_ids[self._consumed] is not None:
                    i = self._consumed
                    self._consumed += 1
                    state.consumed = max(state.consumed, self._consumed)
                    return ObjectRef(ObjectID(state.dyn_ids[i]),
                                     self._core.address)
                if state.done:
                    if state.error is not None:
                        raise state.error
                    return None
                if not state.cond.wait(timeout):
                    raise TimeoutError(
                        f"no streamed item within {timeout}s")

    def __del__(self):
        # reap the owner-side stream state once the handle goes away:
        # immediately if the task finished, else mark it abandoned so
        # _finish_stream reaps it at completion (a finished-but-never-
        # drained stream must not pin its dyn_ids forever)
        try:
            core = self._core
            tid_bin = self._task_id.binary()
            state = core._streaming_states.get(tid_bin)
            if state is None:
                return
            if state.done:
                # finished-but-undrained: free the unconsumed remainder
                # too (they hold zero refs and would leak)
                core._reap_stream_remainder(tid_bin)
            else:
                core._stream_abandoned.add(tid_bin)
        except Exception:
            pass
