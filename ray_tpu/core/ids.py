"""Binary identifiers for jobs, tasks, actors, objects, nodes, and workers.

Design parity: the reference encodes lineage inside its IDs (reference
``src/ray/common/id.h`` — ObjectID = TaskID + index, TaskID embeds ActorID,
ActorID embeds JobID).  We keep that property because object reconstruction
and the ownership protocol depend on being able to recover "which task made
this object" from the ID alone, without a directory lookup.

Layout (bytes):

    JobID    : 4
    ActorID  : 4 (job) + 12 (unique)               = 16
    TaskID   : 16 (actor-or-padding) + 8 (unique)  = 24
    ObjectID : 24 (task) + 4 (big-endian index)    = 28
    NodeID   : 16 random
    WorkerID : 16 random
    PlacementGroupID : 4 (job) + 14 (unique)       = 18

Index semantics for ObjectID match the reference: return objects of a task
use indices 1..n; objects created by ``put`` use a dedicated put-index space
(high bit set) so both can be derived from the producing TaskID.
"""

from __future__ import annotations

import os
import struct
import threading

_NIL_BYTE = b"\xff"


class _EntropyPool:
    """Buffered os.urandom: one getrandom(2) syscall per 1024 draws.

    A single urandom(8) measured ~12 us — the single largest line item in
    task-id generation on nop-task storms.  Thread-safe; the pool is
    refilled wholesale so slices never tear.
    """

    _CHUNK = 8192

    def __init__(self):
        self._lock = threading.Lock()
        self._buf = b""
        self._pos = 0

    def take(self, n: int) -> bytes:
        with self._lock:
            if self._pos + n > len(self._buf):
                self._buf = os.urandom(max(self._CHUNK, n))
                self._pos = 0
            out = self._buf[self._pos:self._pos + n]
            self._pos += n
            return out


_entropy = _EntropyPool()


def _fork_reset():
    # children must not replay the parent's buffered entropy (id collisions)
    global _entropy
    _entropy = _EntropyPool()


if hasattr(os, "register_at_fork"):
    os.register_at_fork(after_in_child=_fork_reset)


class BaseID:
    """An immutable, hashable, fixed-width binary ID."""

    SIZE = 0
    __slots__ = ("_bytes", "_hash", "_hex")

    def __init__(self, id_bytes: bytes):
        if len(id_bytes) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, "
                f"got {len(id_bytes)}"
            )
        self._bytes = bytes(id_bytes)
        self._hash = hash((type(self).__name__, self._bytes))
        self._hex: "str | None" = None

    @classmethod
    def from_random(cls) -> "BaseID":
        return cls(_entropy.take(cls.SIZE))

    @classmethod
    def nil(cls) -> "BaseID":
        return cls(_NIL_BYTE * cls.SIZE)

    @classmethod
    def from_hex(cls, hex_str: str) -> "BaseID":
        return cls(bytes.fromhex(hex_str))

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        # cached: id hexes are compared on hot paths (owner checks run
        # once per get; profiling showed 6+ hex() calls per task)
        h = self._hex
        if h is None:
            h = self._hex = self._bytes.hex()
        return h

    def is_nil(self) -> bool:
        return self._bytes == _NIL_BYTE * self.SIZE

    def __hash__(self) -> int:
        return self._hash

    def __eq__(self, other) -> bool:
        return type(other) is type(self) and other._bytes == self._bytes

    def __lt__(self, other) -> bool:
        return self._bytes < other._bytes

    def __repr__(self) -> str:
        return f"{type(self).__name__}({self._bytes.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseID):
    SIZE = 4
    __slots__ = ()

    @classmethod
    def from_int(cls, value: int) -> "JobID":
        return cls(struct.pack(">I", value))

    def int_value(self) -> int:
        return struct.unpack(">I", self._bytes)[0]


class NodeID(BaseID):
    SIZE = 16
    __slots__ = ()


class WorkerID(BaseID):
    SIZE = 16
    __slots__ = ()


class ActorID(BaseID):
    SIZE = 16
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID) -> "ActorID":
        return cls(job_id.binary() + _entropy.take(cls.SIZE - JobID.SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


class TaskID(BaseID):
    SIZE = 24
    UNIQUE = 8
    __slots__ = ()

    @classmethod
    def for_normal_task(cls, job_id: JobID) -> "TaskID":
        pad = _NIL_BYTE * (ActorID.SIZE - JobID.SIZE)
        return cls(job_id.binary() + pad + _entropy.take(cls.UNIQUE))

    @classmethod
    def for_actor_task(cls, actor_id: ActorID) -> "TaskID":
        return cls(actor_id.binary() + _entropy.take(cls.UNIQUE))

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        """The implicit root task of a driver process."""
        pad = _NIL_BYTE * (ActorID.SIZE - JobID.SIZE)
        return cls(job_id.binary() + pad + b"\x00" * cls.UNIQUE)

    def actor_id(self) -> ActorID:
        return ActorID(self._bytes[: ActorID.SIZE])

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


# High bit of the 4-byte index marks "created by put" rather than "returned
# by the task" — same split as the reference's put/return index spaces.
_PUT_INDEX_FLAG = 0x80000000


class ObjectID(BaseID):
    SIZE = 28
    __slots__ = ()

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        assert 0 < index < _PUT_INDEX_FLAG
        return cls(task_id.binary() + struct.pack(">I", index))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        assert 0 < put_index < _PUT_INDEX_FLAG
        return cls(task_id.binary() + struct.pack(">I", put_index | _PUT_INDEX_FLAG))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[: TaskID.SIZE])

    def job_id(self) -> JobID:
        return self.task_id().job_id()

    def index(self) -> int:
        return struct.unpack(">I", self._bytes[TaskID.SIZE :])[0] & ~_PUT_INDEX_FLAG

    def is_put(self) -> bool:
        raw = struct.unpack(">I", self._bytes[TaskID.SIZE :])[0]
        return bool(raw & _PUT_INDEX_FLAG)


class PlacementGroupID(BaseID):
    SIZE = 18
    __slots__ = ()

    @classmethod
    def of(cls, job_id: JobID) -> "PlacementGroupID":
        return cls(job_id.binary() + _entropy.take(cls.SIZE - JobID.SIZE))

    def job_id(self) -> JobID:
        return JobID(self._bytes[: JobID.SIZE])


class _Counter:
    """Thread-safe monotonically increasing counter starting at 1."""

    def __init__(self):
        self._value = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._value += 1
            return self._value
