"""Ownership-based reference counting and task lifetime management.

Parity: reference ``src/ray/core_worker/reference_count.h`` (distributed
refcount + borrowing) and ``task_manager.h`` (in-flight task tracking,
retries, lineage pinning for reconstruction).

Model: the worker that creates an object (by ``put`` or by submitting the
producing task) is its *owner*.  The owner tracks

- local refs    — live ``ObjectRef`` pythons objects in the owner process,
- submitted refs — uses of the object as an argument of in-flight tasks,
- borrowers     — remote workers that deserialized the ref.

When all three hit zero, the object is freed: dropped from the owner's
memory store and, for shared-memory objects, a free is broadcast to every
raylet holding a copy.  Borrowing workers keep a local count per borrowed
ref and tell the owner when they first see the ref and when their last
local ref dies.

Lineage: the owner keeps the producing TaskSpec of every finished task
whose returns are still referenced, so a lost shared-memory object can be
reconstructed by resubmitting the task (reference
``object_recovery_manager.h``).
"""

from __future__ import annotations

import logging
import threading
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ray_tpu.core.ids import ObjectID, TaskID
from ray_tpu.core.task_spec import TaskSpec

logger = logging.getLogger(__name__)


class Reference:
    """Slots class, not a dataclass: one is allocated per owned/borrowed
    object on the submit hot path, and the three collection fields start
    as shared empty singletons (a set/list allocation each measured ~1 us
    ×3 per task).  Mutating sites replace the singleton first."""

    __slots__ = ("local_refs", "submitted_refs", "borrowers", "owned",
                 "owner_address", "locations", "spilled_on", "spilled_uri",
                 "in_plasma",
                 "producing_task", "contained_ids", "freed")

    _EMPTY_SET: frozenset = frozenset()

    def __init__(self):
        self.local_refs = 0
        self.submitted_refs = 0
        self.borrowers: Set[tuple] = self._EMPTY_SET  # worker addresses
        self.owned = False  # this process is the owner
        self.owner_address: Optional[tuple] = None  # for borrowed refs
        # nodes (raylet addresses) known to hold a shm copy; owner-side only
        self.locations: Set[tuple] = self._EMPTY_SET
        self.spilled_on: Optional[tuple] = None
        self.spilled_uri: Optional[str] = None
        self.in_plasma = False
        # lineage: the task that produces this object (owner-side)
        self.producing_task: Optional[TaskID] = None
        # refs nested inside this object's serialized bytes: pinned (as
        # submitted refs) until this object itself is freed, so readers can
        # always borrow them (parity: the reference records nested ids on
        # the owning reference)
        self.contained_ids: Sequence[ObjectID] = ()
        self.freed = False


class ReferenceCounter:
    """Thread-safe refcount table.

    Locking discipline (parity: the reference posts release callbacks to the
    io_service instead of invoking them under its mutex,
    ``src/ray/core_worker/reference_count.cc``): ``self._lock`` protects only
    the table; the ``on_free`` / ``on_borrow_*`` callbacks are ALWAYS invoked
    after the lock is released.  Callbacks may therefore take other locks
    (e.g. the TaskManager's) without risking lock-order inversion — the
    round-1 AB-BA deadlock was exactly ``remove_local_ref`` (RC lock held)
    → ``evict_lineage`` (wants TM lock) racing ``TaskManager.register``
    (TM lock held) → ``add_owned`` (wants RC lock).
    """

    def __init__(self, on_free: Callable[[ObjectID, Reference], None],
                 on_borrow_added: Callable[[ObjectID, Optional[tuple]], None],
                 on_borrow_removed: Callable[[ObjectID, Optional[tuple]], None]):
        self._lock = threading.RLock()
        self._refs: Dict[ObjectID, Reference] = {}
        self._on_free = on_free
        self._on_borrow_added = on_borrow_added
        self._on_borrow_removed = on_borrow_removed

    # A "release action" is computed under the lock and fired outside it.
    def _fire(self, action: Optional[tuple]) -> None:
        if action is None:
            return
        kind, object_id, payload = action
        try:
            if kind == "free":
                self._on_free(object_id, payload)
                # the freed object's nested refs lose their containment
                # pin (may cascade; we are outside the lock)
                for cid in payload.contained_ids:
                    self.remove_submitted_ref(cid)
            else:  # "borrow_removed"
                self._on_borrow_removed(object_id, payload)
        except Exception:  # callbacks must never poison the caller
            logger.exception("refcount release callback failed for %s",
                             object_id)

    def _get(self, object_id: ObjectID) -> Reference:
        ref = self._refs.get(object_id)
        if ref is None:
            ref = Reference()
            self._refs[object_id] = ref
        return ref

    # -- owner-side -------------------------------------------------------
    def add_owned(self, object_id: ObjectID,
                  producing_task: Optional[TaskID] = None) -> None:
        with self._lock:
            ref = self._get(object_id)
            ref.owned = True
            ref.producing_task = producing_task

    def add_local_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._get(object_id).local_refs += 1

    def set_contained(self, object_id: ObjectID,
                      contained: List[ObjectID]) -> None:
        """Pin refs nested inside ``object_id``'s serialized value for
        the outer object's lifetime (released on its free)."""
        if not contained:
            return
        with self._lock:
            self._get(object_id).contained_ids = list(contained)
            for cid in contained:
                self._get(cid).submitted_refs += 1

    def remove_local_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.local_refs -= 1
            action = self._maybe_release(object_id, ref)
        self._fire(action)

    def add_submitted_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            self._get(object_id).submitted_refs += 1

    def remove_submitted_ref(self, object_id: ObjectID) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            ref.submitted_refs -= 1
            action = self._maybe_release(object_id, ref)
        self._fire(action)

    def add_borrower(self, object_id: ObjectID, borrower: tuple) -> None:
        with self._lock:
            ref = self._get(object_id)
            if ref.borrowers is Reference._EMPTY_SET:
                ref.borrowers = set()
            ref.borrowers.add(borrower)

    def remove_borrower(self, object_id: ObjectID, borrower: tuple) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return
            if ref.borrowers:
                ref.borrowers.discard(borrower)
            action = self._maybe_release(object_id, ref)
        self._fire(action)

    def add_location(self, object_id: ObjectID, node_address: tuple) -> None:
        with self._lock:
            ref = self._get(object_id)
            ref.in_plasma = True
            if ref.locations is Reference._EMPTY_SET:
                ref.locations = set()
            ref.locations.add(node_address)

    def set_spilled(self, object_id: ObjectID, node_address: tuple) -> None:
        with self._lock:
            self._get(object_id).spilled_on = node_address

    def set_spilled_uri(self, object_id: ObjectID, uri: str) -> None:
        """External spill tier: the blob survives the spilling node, so
        the owner records the URI (any node can restore from it)."""
        with self._lock:
            self._get(object_id).spilled_uri = uri

    def get_spilled_uri(self, object_id: ObjectID) -> Optional[str]:
        with self._lock:
            ref = self._refs.get(object_id)
            return ref.spilled_uri if ref is not None else None

    def remove_location(self, object_id: ObjectID, node_address: tuple) -> None:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is not None and ref.locations:
                ref.locations.discard(node_address)

    def get_locations(self, object_id: ObjectID) -> Tuple[List[tuple],
                                                          Optional[tuple]]:
        with self._lock:
            ref = self._refs.get(object_id)
            if ref is None:
                return [], None
            return list(ref.locations), ref.spilled_on

    def get(self, object_id: ObjectID) -> Optional[Reference]:
        with self._lock:
            return self._refs.get(object_id)

    # -- borrower-side ----------------------------------------------------
    def add_borrowed_ref(self, object_id: ObjectID,
                         owner_address: Optional[tuple]) -> None:
        with self._lock:
            ref = self._get(object_id)
            first = ref.local_refs == 0 and not ref.owned
            ref.local_refs += 1
            if ref.owner_address is None:
                ref.owner_address = owner_address
        if first:
            self._on_borrow_added(object_id, owner_address)

    # -- release ----------------------------------------------------------
    def _maybe_release(self, object_id: ObjectID,
                       ref: Reference) -> Optional[tuple]:
        """Called with self._lock held.  Returns the release action to fire
        AFTER the lock is released (never invokes callbacks inline)."""
        if ref.local_refs > 0 or ref.submitted_refs > 0 or ref.borrowers:
            return None
        if ref.freed:
            return None
        ref.freed = True
        del self._refs[object_id]
        if ref.owned:
            return ("free", object_id, ref)
        # last local borrow released: tell the owner
        return ("borrow_removed", object_id, ref.owner_address)

    def owned_count(self) -> int:
        with self._lock:
            return sum(1 for r in self._refs.values() if r.owned)

    def summary(self) -> Dict[str, int]:
        with self._lock:
            return {
                "total": len(self._refs),
                "owned": sum(1 for r in self._refs.values() if r.owned),
                "borrowed": sum(1 for r in self._refs.values() if not r.owned),
                "in_plasma": sum(1 for r in self._refs.values() if r.in_plasma),
            }


@dataclass
class PendingTask:
    spec: TaskSpec
    retries_left: int
    # callbacks fired with (results | None, error | None)
    lineage_footprint: List[ObjectID] = field(default_factory=list)


class TaskManager:
    """Owner-side in-flight task table with retry + lineage bookkeeping.

    The owner registers every submitted task here before handing it to a
    submitter.  On completion the return values are published to the
    memory store; the spec is retained (lineage) while any return object
    may still need reconstruction.  On worker/node failure the task is
    resubmitted if its retry budget allows.

    Locking discipline: the TM lock protects only the pending/lineage
    tables.  All ReferenceCounter calls happen OUTSIDE the TM lock (the RC
    may fire free callbacks that re-enter ``evict_lineage``), so the only
    nesting that ever occurs is "no lock held → RC lock" and "no lock held
    → TM lock" — no AB-BA cycle is possible.
    """

    def __init__(self, reference_counter: ReferenceCounter):
        self._lock = threading.RLock()
        self._pending: Dict[TaskID, PendingTask] = {}
        self._lineage: Dict[TaskID, TaskSpec] = {}
        self._rc = reference_counter

    @staticmethod
    def _arg_ids(spec: TaskSpec):
        """Every object id a task's flight must pin: direct ref args plus
        refs nested inside inlined values."""
        for arg in spec.args:
            if arg.object_id is not None:
                yield arg.object_id
            yield from arg.contained_ids

    def register(self, spec: TaskSpec) -> List[ObjectID]:
        """Registers the flight; returns the return ids so the submitter
        can build its ObjectRefs without recomputing them (they cost one
        hash construction each on the hot path)."""
        rets = spec.return_ids()
        for ret in rets:
            self._rc.add_owned(ret, producing_task=spec.task_id)
        for oid in self._arg_ids(spec):
            self._rc.add_submitted_ref(oid)
        with self._lock:
            self._pending[spec.task_id] = PendingTask(
                spec=spec, retries_left=spec.max_retries)
        return rets

    def is_pending(self, task_id: TaskID) -> bool:
        with self._lock:
            return task_id in self._pending

    def pending_spec(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            entry = self._pending.get(task_id)
            return entry.spec if entry is not None else None

    def num_pending(self) -> int:
        with self._lock:
            return len(self._pending)

    def complete(self, task_id: TaskID) -> Optional[TaskSpec]:
        """Mark done; returns the spec (now lineage) if it was pending."""
        with self._lock:
            entry = self._pending.pop(task_id, None)
            if entry is None:
                return None
            self._lineage[task_id] = entry.spec
        for oid in self._arg_ids(entry.spec):
            self._rc.remove_submitted_ref(oid)
        return entry.spec

    def take_for_retry(self, task_id: TaskID) -> Optional[TaskSpec]:
        """Consume one retry; returns the bumped spec or None if exhausted."""
        with self._lock:
            entry = self._pending.get(task_id)
            if entry is None or entry.retries_left <= 0:
                return None
            entry.retries_left -= 1
            entry.spec.attempt_number += 1
            return entry.spec

    def fail(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            entry = self._pending.pop(task_id, None)
            if entry is None:
                return None
        for oid in self._arg_ids(entry.spec):
            self._rc.remove_submitted_ref(oid)
        return entry.spec

    def lineage_spec(self, task_id: TaskID) -> Optional[TaskSpec]:
        with self._lock:
            return self._lineage.get(task_id)

    def resubmit_for_reconstruction(self, task_id: TaskID
                                    ) -> Optional[TaskSpec]:
        """Move a finished task back to pending for lineage reconstruction."""
        with self._lock:
            spec = self._lineage.get(task_id)
            if spec is None:
                return None
            if task_id in self._pending:
                return None  # already being re-executed
            spec.attempt_number += 1
            self._pending[task_id] = PendingTask(spec=spec, retries_left=0)
        for oid in self._arg_ids(spec):
            self._rc.add_submitted_ref(oid)
        return spec

    def evict_lineage(self, task_id: TaskID) -> None:
        with self._lock:
            self._lineage.pop(task_id, None)
