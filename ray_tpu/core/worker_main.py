"""Entry point for spawned worker processes.

Parity: the reference's python worker `default_worker.py` — connect to the
local raylet + GCS, then run the task execution loop on the main thread.
"""

from __future__ import annotations

import argparse
import logging
import os
import sys


def _install_cancel_sigint_handler() -> None:
    """Task cancellation delivers a real SIGINT to this process's main
    thread (worker.py handle_cancel_task -> pthread_kill).  Gate it on
    the per-thread interrupt window: inside a task body it raises
    KeyboardInterrupt (the reference's cancel semantics); landing in
    the commit phase — after the body returned, while the reply is
    being shipped — it is swallowed so the exec loop (and the computed
    reply) survive the race."""
    import signal

    def handler(signum, frame):
        from ray_tpu.core.worker import INTERRUPT_WINDOW
        if getattr(INTERRUPT_WINDOW, "open", False):
            raise KeyboardInterrupt
        # cancel raced task completion: ignore — the cancel reply path
        # already settles the task owner-side

    signal.signal(signal.SIGINT, handler)


def main() -> None:
    import time
    t0 = time.perf_counter()
    trace = os.environ.get("RAY_TPU_BOOT_TRACE")

    def mark(label):
        if trace:
            sys.stderr.write(
                f"BOOT {label} {1000 * (time.perf_counter() - t0):.1f}ms\n")
            sys.stderr.flush()

    from ray_tpu.core.node import maybe_arm_pdeathsig
    maybe_arm_pdeathsig()
    mark("pdeathsig")
    parser = argparse.ArgumentParser()
    parser.add_argument("--raylet", required=True)
    parser.add_argument("--gcs", required=True)
    parser.add_argument("--node-id", required=True)
    parser.add_argument("--store-path", required=True)
    parser.add_argument("--store-capacity", type=int, required=True)
    parser.add_argument("--session-dir", required=True)
    parser.add_argument("--job-id", default=None)
    args = parser.parse_args()

    logging.basicConfig(
        level=os.environ.get("RAY_TPU_LOG_LEVEL", "INFO"),
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    if os.environ.get("RAY_TPU_WORKER_FAULTHANDLER"):
        import faulthandler

        faulthandler.enable()
        faulthandler.dump_traceback_later(
            float(os.environ["RAY_TPU_WORKER_FAULTHANDLER"]), repeat=True)

    # Workers never own TPU chips unless a task leases them; keep jax (if
    # user code imports it) off the real accelerator by default so that N
    # workers on one host don't fight over the chip.  Training workers
    # explicitly clear this (see ray_tpu.train).
    os.environ.setdefault("JAX_PLATFORMS", os.environ.get(
        "RAY_TPU_WORKER_JAX_PLATFORMS", "cpu"))

    from ray_tpu.core.ids import JobID, NodeID
    from ray_tpu.core.worker import CoreWorker
    _install_cancel_sigint_handler()
    mark("imports")

    def parse_addr(s: str):
        host, port = s.rsplit(":", 1)
        return (host, int(port))

    worker = CoreWorker(
        mode="worker",
        gcs_address=parse_addr(args.gcs),
        raylet_address=parse_addr(args.raylet),
        node_id=NodeID.from_hex(args.node_id),
        store_path=args.store_path,
        store_capacity=args.store_capacity,
        session_dir=args.session_dir,
        job_id=JobID.from_hex(args.job_id) if args.job_id else None,
    )
    mark("core_worker_ready")
    try:
        worker.run_exec_loop()
    finally:
        worker.shutdown()
    sys.exit(0)


if __name__ == "__main__":
    main()
