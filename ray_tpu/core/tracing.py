"""Native request-scoped distributed tracing: context, spans, buffer.

Parity motivation: the reference runtime's OpenTelemetry integration
(``util/tracing/tracing_helper.py`` here) is opt-in, needs an external
exporter, and covers none of the serve hops — when a p99 SLO burns, the
``ray_tpu_serve_*`` histograms say *that* it burned, not *where*.  This
module is the always-available half: a trace context (trace_id +
parent span_id) is born at the serve HTTP ingress and at driver-side
``remote()`` submission, rides existing RPC payloads / task specs
through every hop, and each process buffers completed spans here until
its telemetry flush loop ships them to the GCS trace ring
(``report_trace_spans``, drop-don't-block) where **tail-based
sampling** decides retention at trace completion (``core/gcs.py``).

Cost discipline:

- ``tracing_enabled`` off: ingress/submit sites never create a context,
  every hop sees ``ctx is None`` and skips — nothing rides the wire,
  nothing is buffered.  The tag happens ONCE at the trace's birth; no
  per-hop sampling branch exists.
- enabled: a span is one small dict append into a bounded deque (oldest
  drop when the buffer outpaces the flush loop).  Producers never do
  I/O; the flush loops that do live with their owners.

Span timestamps are wall-clock (``time.time()``), corrected onto the
GCS timebase at drain with the same clock offset the telemetry spans
use (``telemetry.measure_clock_offset``), so a cross-host trace tree
lines up without per-consumer correction.

Context propagation conventions:

- RPC payload dicts carry the carrier under the ``"trace"`` key;
  ``rpc.Connection._dispatch`` re-activates it for the handler (the
  ``trace-propagation`` rtpu-check rule keeps serve / submit-path call
  sites honest).
- Task specs carry it inside ``TaskSpec.trace_context`` (the native
  ``trace_id``/``span_id`` keys coexist with the optional W3C
  ``traceparent`` of the OTel helper).
- In-process, the ambient context is a :data:`contextvars.ContextVar`
  (works across threads and asyncio tasks).
"""

from __future__ import annotations

import itertools
import os
import time
from collections import deque
from contextvars import ContextVar
from typing import Any, Dict, List, Optional

__all__ = [
    "enabled", "current", "use_ctx", "Span", "start_trace", "start_span",
    "record", "drain", "ctx_of", "new_trace_id",
]

# ---------------------------------------------------------------------------
# enable gate (mirrors telemetry.enabled(): one cached bool per process)
# ---------------------------------------------------------------------------

_enabled: Optional[bool] = None


def enabled() -> bool:
    global _enabled
    if _enabled is None:
        env = os.environ.get("RAY_TPU_TRACING_ENABLED")
        if env is not None:
            _enabled = env.lower() in ("1", "true", "yes")
        else:
            try:
                from ray_tpu.core.config import get_config
                _enabled = bool(getattr(get_config(), "tracing_enabled",
                                        True))
            except Exception:  # noqa: BLE001 — config unavailable: stay on
                _enabled = True
    return _enabled


def _reset_for_tests(force: Optional[bool] = None) -> None:
    global _enabled
    _enabled = force
    _buf.clear()


# ---------------------------------------------------------------------------
# ids + ambient context
# ---------------------------------------------------------------------------

#: id generation: a process-local PRNG seeded ONCE from os.urandom.
#: urandom/getpid are multi-microsecond syscalls on hardened kernels —
#: paying one per span put tracing at 14% of the sync-task microbench;
#: getrandbits is ~0.3us.  Fork safety comes from os.register_at_fork
#: (workers FORK from the zygote; an inherited RNG/prefix would collide
#: span ids across processes and mis-link assembled trees) plus a lazy
#: None check for spawn-fresh processes.
_rng: Optional[Any] = None  # random.Random, imported lazily
_id_prefix = ""
_span_counter = itertools.count(1)

_current: "ContextVar[Optional[Dict[str, str]]]" = ContextVar(
    "rtpu_trace_ctx", default=None)


def _reseed() -> None:
    global _rng, _id_prefix, _span_counter
    import random
    _rng = random.Random(int.from_bytes(os.urandom(16), "little"))
    _id_prefix = f"{_rng.getrandbits(32):08x}"
    _span_counter = itertools.count(1)


if hasattr(os, "register_at_fork"):  # CPython >= 3.7, POSIX
    os.register_at_fork(after_in_child=_reseed)


def new_trace_id() -> str:
    """Fully random 64-bit hex id — it feeds the deterministic
    tail-sampling hash, so it must be uniform."""
    if _rng is None:
        _reseed()
    return f"{_rng.getrandbits(64):016x}"


def _new_span_id() -> str:
    if _rng is None:
        _reseed()
    return f"{_id_prefix}{next(_span_counter):08x}"


def current() -> Optional[Dict[str, str]]:
    """The ambient trace carrier (``{"trace_id", "span_id"}``) or None."""
    return _current.get()


def set_current(ctx: Optional[Dict[str, str]]):
    """Low-level: activate ``ctx``; returns the reset token."""
    return _current.set(ctx)


def reset_current(token) -> None:
    _current.reset(token)


class use_ctx:
    """``with use_ctx(ctx): ...`` — activate a carrier for a block.
    ``ctx=None`` deactivates (children see no trace)."""

    __slots__ = ("_ctx", "_token")

    def __init__(self, ctx: Optional[Dict[str, str]]):
        self._ctx = ctx
        self._token = None

    def __enter__(self):
        self._token = _current.set(self._ctx)
        return self._ctx

    def __exit__(self, *exc):
        _current.reset(self._token)
        return False


def ctx_of(carrier: Optional[Dict[str, str]]
           ) -> Optional[Dict[str, str]]:
    """Extract the native context from a mixed carrier (a TaskSpec
    ``trace_context`` may also hold the OTel ``traceparent``)."""
    if not carrier:
        return None
    tid = carrier.get("trace_id")
    sid = carrier.get("span_id")
    if tid is None or sid is None:
        return None
    return {"trace_id": tid, "span_id": sid}


# ---------------------------------------------------------------------------
# span buffer
# ---------------------------------------------------------------------------

#: bounded pending-span buffer (oldest drop; the flush loop drains it
#: every metrics_report_period_s).  Appends/popleft are GIL-atomic, so
#: batcher threads and the io loop share it without a lock.
_buf: "deque[Dict[str, Any]]" = deque(maxlen=8192)
#: spans displaced by the bound before any flush (diagnostic; GIL int
#: increment — a lock would cost more than the count is worth)
_dropped = 0

#: optional span-completion sink (the flight recorder registers one so
#: span completions land in the crash-surviving ring as well as the
#: flush buffer).  One global load + None test when nothing registered.
_span_sink = None


def dropped() -> int:
    """Spans this process dropped to the buffer bound (never flushed)."""
    return _dropped


def set_span_sink(fn) -> None:
    """Install (or clear, with None) the per-process span-completion
    sink.  The sink must be cheap and must never raise."""
    global _span_sink
    _span_sink = fn


def _append(rec: Dict[str, Any]) -> None:
    global _dropped
    if len(_buf) == _buf.maxlen:
        _dropped += 1
    _buf.append(rec)
    sink = _span_sink
    if sink is not None:
        try:
            sink(rec)
        except Exception:  # noqa: BLE001 — forensics never breaks tracing
            pass


class Span:
    """One in-flight span.  Create via :func:`start_trace` /
    :func:`start_span`; finish with :meth:`end` (idempotent)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "start",
                 "tags", "root", "_done")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str, root: bool,
                 tags: Optional[Dict[str, Any]]):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.start = time.time()
        self.tags = tags
        self.root = root
        self._done = False

    def ctx(self) -> Dict[str, str]:
        """Carrier for children of this span."""
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    def set_tag(self, key: str, value: Any) -> None:
        if self.tags is None:
            self.tags = {}
        self.tags[key] = value

    def end(self, status: str = "ok", **tags: Any) -> None:
        if self._done:
            return
        self._done = True
        if tags:
            if self.tags is None:
                self.tags = {}
            self.tags.update(tags)
        rec: Dict[str, Any] = {
            "trace_id": self.trace_id, "span_id": self.span_id,
            "parent_id": self.parent_id, "name": self.name,
            "start": self.start, "end": time.time(), "status": status,
        }
        if self.root:
            rec["root"] = True
        if self.tags:
            rec["tags"] = self.tags
        _append(rec)


def start_trace(name: str, **tags: Any) -> Optional[Span]:
    """Born at an ingress: a fresh trace whose root span decides tail
    retention when it ends.  None when tracing is disabled — every
    downstream hop then short-circuits on the absent context."""
    if not enabled():
        return None
    return Span(new_trace_id(), _new_span_id(), None, name, True,
                tags or None)


def start_span(name: str, parent: Optional[Dict[str, str]] = None,
               **tags: Any) -> Optional[Span]:
    """Child span under ``parent`` (default: the ambient context).
    None when there is no trace to join — untraced requests pay one
    ContextVar read per hop, nothing more."""
    if parent is None:
        parent = _current.get()
        if parent is None:
            return None
    tid = parent.get("trace_id")
    if tid is None:
        return None
    return Span(tid, _new_span_id(), parent.get("span_id"), name, False,
                tags or None)


def record(name: str, start: float, end: float,
           parent: Optional[Dict[str, str]] = None, status: str = "ok",
           **tags: Any) -> None:
    """One-shot child span from precomputed wall stamps (hot paths that
    already hold their own timestamps)."""
    if parent is None:
        parent = _current.get()
        if parent is None:
            return
    tid = parent.get("trace_id")
    if tid is None:
        return
    rec: Dict[str, Any] = {
        "trace_id": tid, "span_id": _new_span_id(),
        "parent_id": parent.get("span_id"), "name": name,
        "start": start, "end": end, "status": status,
    }
    if tags:
        rec["tags"] = tags
    _append(rec)


def pending() -> int:
    return len(_buf)


def drain(source: str) -> List[Dict[str, Any]]:
    """Pop buffered spans, clock-corrected onto the GCS timebase and
    stamped with their source process (same contract as
    ``telemetry.drain_spans``)."""
    if not _buf:
        return []
    from ray_tpu.core import telemetry as _tm
    off = _tm.clock_offset()
    out: List[Dict[str, Any]] = []
    while _buf:
        try:
            rec = _buf.popleft()
        except IndexError:  # racing drains (tests)
            break
        rec["start"] += off
        rec["end"] += off
        rec["source"] = source
        out.append(rec)
    return out
