"""Crash-surviving flight recorder: the last seconds of every process.

Every process of the fleet (GCS, raylet, worker, driver) keeps a
bounded mmap-backed ring file in the session dir recording its recent
state transitions — span completions, the warning-level log tail, task
start/finish with task/actor identity, lease grants, serve batch
steps, WAL positions.  The in-process telemetry buffers drain on a
~2-5 s flush loop, so the most interesting seconds of any incident are
exactly the ones a SIGKILL destroys; the ring is a *file*, so its
dirty pages survive the process and a surviving raylet (or the head
supervisor, for a raylet/GCS death) can read the dead process's tail
and ship it to the GCS incident journal (core/gcs.py).

Disciplines (same contracts as the PR-5 profiler and PR-11 WAL):

* **Off the hot path**: ``record()`` with the recorder disabled is one
  module-global load + ``None`` test.  Enabled, it is one struct pack
  + crc32 + mmap slice copy under a lock (~1-2 us) — no syscall, no
  fsync (mmap dirty pages of a file survive SIGKILL; only an OS crash
  loses them, which is out of scope).
* **Fixed-size binary frames**: 256 bytes each, CRC32-framed like the
  WAL.  A SIGKILL mid-copy leaves exactly one torn frame, which the
  reader detects by CRC and drops — "loses at most one frame".
* **Catalogued vocabulary**: every event type written anywhere in the
  tree must be declared in :data:`EVENT_TYPES` below; the rtpu-check
  ``flight-vocab`` rule (tools/check/project.py) enforces it the way
  the failpoint registry enforces site documentation.

Ring file anatomy (``<session_dir>/flight/flight-<source>-<pid>.ring``)::

    header (32 B): magic RTPUFLT1 | u32 frame_size | u32 nframes
                   | u32 pid | 12 B source (NUL-padded)
    frame (256 B): u32 crc32(rest) | u64 seq | f64 ts | u8 type
                   | u16 detail_len | detail bytes | zero pad

Frames are written at ``seq % nframes``; the reader collects every
CRC-valid frame and sorts by seq, so ordering survives the wrap.  One
ring per process: in the head process (GCS + raylet co-located) the
first ``init`` wins and both planes share the ring — the source label
names the initializer, the pid is what death-path readers key on.
"""

from __future__ import annotations

import logging
import mmap
import os
import struct
import threading
import zlib
from typing import Any, Dict, List, Optional

logger = logging.getLogger(__name__)

__all__ = ["EVENT_TYPES", "init", "enabled", "record", "stats", "close",
           "ring_path", "rings_for_pid", "read_ring"]

#: the complete vocabulary of recordable event types.  Writers pass one
#: of these keys to :func:`record`; the ``flight-vocab`` static rule
#: rejects any literal not declared here, so the postmortem renderer
#: (and anyone reading a ring) can rely on this table as the single
#: legend.  Order matters: the frame stores the type as an index into
#: the sorted key list, so renames are safe but the set is append-only
#: within a session.
EVENT_TYPES: Dict[str, str] = {
    "alert": "GCS-side alert transition (rule, from -> to)",
    "batch_step": "serve continuous-batching decode step "
                  "(deployment, batch size, step ms)",
    "lease_grant": "raylet granted a worker lease (pid, resources)",
    "log": "WARNING-or-worse log record tail",
    "mark": "free-form state transition (boot, shutdown, recovery)",
    "node_dead": "GCS marked a node dead (node id, reason)",
    "span": "trace span completion (name, status, duration)",
    "task_finish": "executor finished a task body (status)",
    "task_start": "executor began a task body "
                  "(function, task/actor/job identity)",
    "task_submit": "owner submitted a task (function, task id)",
    "wal_append": "GCS WAL position after an append (type, seq, bytes)",
    "worker_dead": "raylet observed a worker death (pid, reason)",
}

MAGIC = b"RTPUFLT1"
FRAME_SIZE = 256
_HDR = struct.Struct("<8sIII12s")       # magic, frame, nframes, pid, source
_FRM = struct.Struct("<IQdBH")          # crc, seq, ts, type idx, detail len
_DETAIL_MAX = FRAME_SIZE - _FRM.size
_TYPE_LIST = sorted(EVENT_TYPES)
_TYPE_IDX = {t: i for i, t in enumerate(_TYPE_LIST)}


def ring_path(session_dir: str, source: str, pid: Optional[int] = None
              ) -> str:
    return os.path.join(session_dir, "flight",
                        f"flight-{source}-{pid or os.getpid()}.ring")


class FlightRecorder:
    """One process's ring writer.  Thread-safe; never raises out of
    :meth:`record` (forensics must not take the plane down)."""

    def __init__(self, source: str, session_dir: str,
                 ring_bytes: int = 1 << 18):
        self.source = source
        self.session_dir = session_dir
        self.nframes = max(16, (int(ring_bytes) - _HDR.size) // FRAME_SIZE)
        self.path = ring_path(session_dir, source)
        os.makedirs(os.path.dirname(self.path), exist_ok=True)
        size = _HDR.size + self.nframes * FRAME_SIZE
        fd = os.open(self.path, os.O_CREAT | os.O_RDWR | os.O_TRUNC, 0o644)
        try:
            os.ftruncate(fd, size)
            self._mm = mmap.mmap(fd, size)
        finally:
            os.close(fd)
        self._mm[:_HDR.size] = _HDR.pack(
            MAGIC, FRAME_SIZE, self.nframes, os.getpid(),
            source.encode()[:12].ljust(12, b"\0"))
        self._lock = threading.Lock()
        self._seq = 0
        self._scratch = bytearray(FRAME_SIZE)

    def record(self, etype: str, detail: str = "") -> None:
        import time
        idx = _TYPE_IDX.get(etype)
        if idx is None:  # undeclared type: flight-vocab catches it in CI
            idx = _TYPE_IDX["mark"]
            detail = f"{etype}: {detail}"
        payload = detail.encode("utf-8", "replace")[:_DETAIL_MAX]
        buf = self._scratch
        try:
            with self._lock:
                seq = self._seq
                self._seq = seq + 1
                _FRM.pack_into(buf, 0, 0, seq, time.time(), idx,
                               len(payload))
                buf[_FRM.size:_FRM.size + len(payload)] = payload
                end = _FRM.size + len(payload)
                if end < FRAME_SIZE:
                    buf[end:] = b"\0" * (FRAME_SIZE - end)
                struct.pack_into("<I", buf, 0, zlib.crc32(buf[4:]))
                off = _HDR.size + (seq % self.nframes) * FRAME_SIZE
                self._mm[off:off + FRAME_SIZE] = buf
        except (ValueError, OSError):  # mmap closed mid-shutdown
            pass

    def stats(self) -> Dict[str, Any]:
        return {"path": self.path, "frames_recorded": self._seq,
                "nframes": self.nframes}

    def close(self, unlink: bool = False) -> None:
        """``unlink=True`` on graceful exit: a surviving ring for a
        dead pid then MEANS a crash — death-path readers need no
        reason heuristics."""
        try:
            self._mm.close()
        except (ValueError, OSError):
            pass
        if unlink:
            try:
                os.unlink(self.path)
            except OSError:
                pass


# -- module singleton (one ring per process; first init wins) -----------
_recorder: Optional[FlightRecorder] = None
_init_args: Optional[tuple] = None
_log_handler: Optional[logging.Handler] = None


class _FlightLogHandler(logging.Handler):
    """WARNING+ log tail into the ring — the last log lines of a dead
    process are usually the first thing a postmortem wants."""

    def emit(self, rec: logging.LogRecord) -> None:
        r = _recorder
        if r is None:
            return
        try:
            r.record("log", f"{rec.levelname} {rec.name}: "
                            f"{rec.getMessage()}")
        except Exception:  # noqa: BLE001 — never recurse into logging
            pass


def init(source: str, session_dir: Optional[str],
         config: Any = None) -> None:
    """Open this process's ring.  First init wins (the head process
    hosts both the GCS and a raylet — they share one per-process ring);
    disabled by ``flight_recorder_enabled=False``, in which case the
    hot path stays a single None test."""
    global _recorder, _init_args
    if _recorder is not None or not session_dir:
        return
    _init_args = (source, session_dir, config)
    if config is not None and not getattr(config,
                                          "flight_recorder_enabled", True):
        return
    _attach(source, session_dir, config)


def _attach(source: str, session_dir: str, config: Any) -> None:
    global _recorder, _log_handler
    try:
        rec = FlightRecorder(
            source, session_dir,
            ring_bytes=int(getattr(config, "flight_ring_bytes", 1 << 18)
                           if config is not None else 1 << 18))
    except OSError:
        logger.exception("flight recorder init failed; disabled")
        return
    _recorder = rec
    rec.record("mark", f"{source} flight recorder online")
    if _log_handler is None:
        _log_handler = _FlightLogHandler(level=logging.WARNING)
        logging.getLogger().addHandler(_log_handler)
    # span completions ride the ring too (only costs anything while
    # tracing is enabled; the sink itself is one function pointer)
    from ray_tpu.core import tracing as _trace
    _trace.set_span_sink(_span_sink)


def _span_sink(span: Dict[str, Any]) -> None:
    r = _recorder
    if r is None:
        return
    dur_ms = (span.get("end", 0.0) - span.get("start", 0.0)) * 1e3
    r.record("span", f"{span.get('name')} {span.get('status', 'ok')} "
                     f"{dur_ms:.2f}ms")


def enabled() -> bool:
    return _recorder is not None


def record(etype: str, detail: str = "") -> None:
    """Hot-path write: no-op (one None test) when the recorder is off."""
    r = _recorder
    if r is not None:
        r.record(etype, detail)


def stats() -> Optional[Dict[str, Any]]:
    r = _recorder
    return r.stats() if r is not None else None


def close(unlink: bool = False) -> None:
    global _recorder
    r, _recorder = _recorder, None
    if r is not None:
        r.close(unlink=unlink)


def _reset_for_tests(force: Optional[bool] = None) -> None:
    """Bench/test toggle (same contract as tracing._reset_for_tests):
    ``force=False`` detaches the recorder (off block), ``force=True``
    re-attaches it on the saved init args, ``None`` restores the
    config-driven state."""
    global _recorder
    if force is False:
        r, _recorder = _recorder, None
        if r is not None:
            r.close()
        return
    if _recorder is None and _init_args is not None:
        source, session_dir, config = _init_args
        if force or config is None or getattr(
                config, "flight_recorder_enabled", True):
            _attach(source, session_dir, config)


# -- death-path readers --------------------------------------------------

def rings_for_pid(session_dir: str, pid: int) -> List[str]:
    """Ring files a dead process with this pid left behind."""
    d = os.path.join(session_dir, "flight")
    try:
        names = os.listdir(d)
    except OSError:
        return []
    suffix = f"-{pid}.ring"
    return sorted(os.path.join(d, n) for n in names
                  if n.startswith("flight-") and n.endswith(suffix))


def read_ring(path: str, limit: int = 200) -> Optional[Dict[str, Any]]:
    """Decode a ring file: every CRC-valid frame, seq-ordered, torn
    frames counted and dropped (the ring-file analogue of the WAL's
    torn-tail truncation).  Returns None for a missing/foreign file."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        return None
    if len(blob) < _HDR.size:
        return None
    magic, frame_size, nframes, pid, source = _HDR.unpack_from(blob, 0)
    if magic != MAGIC or frame_size != FRAME_SIZE:
        return None
    frames: List[Dict[str, Any]] = []
    torn = 0
    for i in range(min(nframes, (len(blob) - _HDR.size) // FRAME_SIZE)):
        off = _HDR.size + i * FRAME_SIZE
        frame = blob[off:off + FRAME_SIZE]
        crc, seq, ts, idx, dlen = _FRM.unpack_from(frame, 0)
        if crc == 0 and seq == 0 and ts == 0.0 and dlen == 0 and idx == 0 \
                and frame[_FRM.size:] == b"\0" * (FRAME_SIZE - _FRM.size):
            continue  # never-written slot
        if crc != zlib.crc32(frame[4:]) or dlen > _DETAIL_MAX:
            torn += 1
            continue
        frames.append({
            "seq": seq, "ts": ts,
            "type": _TYPE_LIST[idx] if idx < len(_TYPE_LIST) else "mark",
            "detail": frame[_FRM.size:_FRM.size + dlen].decode(
                "utf-8", "replace"),
        })
    frames.sort(key=lambda fr: fr["seq"])
    return {"source": source.rstrip(b"\0").decode("utf-8", "replace"),
            "pid": pid, "torn": torn, "frames": frames[-limit:]}
